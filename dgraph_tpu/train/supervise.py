"""Self-healing train supervisor: restart-and-resume around exit 17.

``train/elastic.py`` documents the restart contract — a wedged device makes
:class:`~dgraph_tpu.train.elastic.StepWatchdog` hard-exit the process with
:data:`~dgraph_tpu.train.elastic.WEDGED_EXIT_CODE` (17), and "the launcher
treats that exit as restart-and-resume" — but until this module the repo
shipped no launcher.  ``python -m dgraph_tpu.train.supervise`` is it:

- runs the training entrypoint as a subprocess;
- restarts it on exit 17 (wedge), on crash (any nonzero exit, optional),
  and on an attempt-level wall timeout, with exponential backoff and a
  max-restart budget;
- resumption is the child's job (restore ``latest_step()`` from its
  checkpoint dir); the supervisor reads the same ``latest_step()`` before
  each attempt so the lineage records what each attempt resumed from;
- exports the attempt ordinal as ``DGRAPH_CHAOS_ATTEMPT`` so a chaos
  clause (:mod:`dgraph_tpu.chaos`) can target exactly one attempt — the
  end-to-end recovery test injects a wedge on attempt 0 and proves the
  resumed run is bit-identical to a fault-free one;
- emits ONE JSON-parseable lineage record on EVERY exit path (the bench
  supervisor's discipline): attempt count, per-attempt exit codes and
  wall times, resume steps, and a RunHealth record.

The supervisor itself never touches the accelerator: reading
``latest_step`` is a directory listing, and no jax API is called — a
wedged lease can hang a child, never the process that must outlive it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Optional

# bench.py's wedge-surviving supervisor loads this file STANDALONE (by
# path, registered as ``_dgraph_train_supervise``) so its backend-probe
# loop can run under this exact restart/backoff/budget policy without
# importing the dgraph_tpu package — whose ``__init__`` imports jax (the
# same contract obs/health.py and obs/spans.py carry).  The spans twin is
# registered in sys.modules before this module is exec'd; the literal
# fallbacks are the canonical contract values, pinned against the package
# ones in tests/test_plan_shards.py.  Keyed on OUR module name so a
# normal package import never takes this branch, even in a process that
# also loaded bench's standalone twins.
if __name__ == "_dgraph_train_supervise":  # standalone (bench supervisor)
    spans = sys.modules["_dgraph_obs_spans"]
    WEDGED_EXIT_CODE = 17  # train.elastic.WEDGED_EXIT_CODE
    ATTEMPT_ENV_VAR = "DGRAPH_CHAOS_ATTEMPT"  # chaos.ATTEMPT_ENV_VAR
    RANK_ENV_VAR = "DGRAPH_RANK"  # utils.env.RANK_ENV_VAR
    RANK_LOST_EXIT_CODE = 19  # comm.membership.RANK_LOST_EXIT_CODE
    RANK_JOIN_EXIT_CODE = 23  # comm.membership.RANK_JOIN_EXIT_CODE
else:
    import dgraph_tpu.obs.spans as spans  # jax-free (lint-enforced)
    from dgraph_tpu.chaos import ATTEMPT_ENV_VAR
    from dgraph_tpu.comm.membership import (
        RANK_JOIN_EXIT_CODE,
        RANK_LOST_EXIT_CODE,
    )
    from dgraph_tpu.utils.env import RANK_ENV_VAR
    from dgraph_tpu.train.elastic import WEDGED_EXIT_CODE


@dataclasses.dataclass
class Config:
    """Train supervisor (``--cmd "python -m ..."`` is the child entrypoint;
    restarts on exit 17/crash with exponential backoff)."""

    cmd: str = ""  # shell-style child command line (shlex-split);
    # with --ranks N, "{rank}"/"{world}" placeholders are substituted
    ranks: int = 0  # 0 = single child; N > 0 = multi-rank group mode
    rank_loss_grace_s: float = 30.0  # survivors' window to exit 19
    max_restarts: int = 8  # restart budget (attempts = budget + 1)
    backoff_s: float = 1.0  # first restart delay
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    restart_on_crash: bool = True  # False: only exit 17 restarts
    attempt_timeout_s: float = 0.0  # 0 = none; kill + restart past this
    budget_s: float = 0.0  # 0 = none; overall fail-fast wall budget
    stderr_path: str = ""  # capture child stderr here (truncated/attempt)
    ckpt_dir: str = ""  # lineage: record latest_step() resume points
    log_path: str = "logs/supervise.jsonl"
    indent: int = 0


def _latest_step(ckpt_dir: str) -> Optional[int]:
    """latest_step without importing the checkpoint module's orbax path at
    module import time (it is jax-free, but keep the supervisor's import
    surface minimal and explicit)."""
    if not ckpt_dir:
        return None
    from dgraph_tpu.train.checkpoint import latest_step

    return latest_step(ckpt_dir)


def _backoff_delay(attempt: int, backoff_s: float, backoff_factor: float,
                   backoff_max_s: float) -> float:
    """The ONE backoff schedule both supervisors run (exponential,
    capped; attempt 0 never waits) — pinned by the fake-clock tests, and
    shared so the single- and group-mode schedules cannot drift."""
    if not attempt:
        return 0.0
    return min(backoff_s * backoff_factor ** (attempt - 1), backoff_max_s)


def _final_error(rc, last_outcome: str, restarts: int, *, max_restarts: int,
                 budget_s: float, budget_exhausted: bool, gave_up: bool,
                 stopped_on_loss: bool = False, stopped_on_join: bool = False,
                 what: str = "child"):
    """(error, wedge) summary shared by both supervisors' lineages."""
    if rc == 0:
        return None, None
    if budget_exhausted:
        exhausted = f"; wall budget ({budget_s:g}s) exhausted"
    elif stopped_on_loss:
        exhausted = "; stopped on rank loss (no shrink path)"
    elif stopped_on_join:
        exhausted = "; stopped on rank join (no grow path)"
    elif gave_up:
        exhausted = f"; restart budget ({max_restarts}) exhausted"
    else:
        exhausted = ""
    error = (
        f"{what} exited {rc} ({last_outcome}) after {restarts} restart(s)"
        + exhausted
    )
    wedge = (
        "watchdog_timeout" if last_outcome in ("wedged", "timeout")
        else "stage_failure"
    )
    return error, wedge


def _append_jsonl(path: str, rec: dict) -> None:
    """Plain JSONL append — ExperimentLog calls ``jax.process_index()``
    (backend init), which the supervisor must never do."""
    if not path:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec) + "\n")


def _ledger_mod():
    """The perf-ledger module without ever importing the dgraph_tpu
    package (whose ``__init__`` imports jax — the supervisor contract).
    Prefers an already-loaded twin (package import or bench's standalone
    ``_dgraph_obs_ledger``), else path-loads ledger.py standalone; None
    when unavailable (lineage emission must never depend on it)."""
    for name in ("dgraph_tpu.obs.ledger", "_dgraph_obs_ledger"):
        mod = sys.modules.get(name)
        if mod is not None:
            return mod
    try:
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "obs", "ledger.py",
        )
        spec = importlib.util.spec_from_file_location(
            "_dgraph_obs_ledger", path
        )
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_dgraph_obs_ledger"] = mod
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None


def _ledger_ingest(lineage: dict) -> None:
    """Best-effort perf-ledger hook for a sealed lineage record: off by
    default (DGRAPH_LEDGER_DIR opts in), and no failure mode — the
    ledger is a passenger on the supervisor, never a dependency."""
    try:
        mod = _ledger_mod()
        if mod is not None:
            mod.maybe_ingest(
                lineage, source="train.supervise", default_on=False
            )
    except Exception:
        pass


def supervise(
    argv: list,
    *,
    max_restarts: int = 8,
    backoff_s: float = 1.0,
    backoff_factor: float = 2.0,
    backoff_max_s: float = 60.0,
    restart_on_crash: bool = True,
    attempt_timeout_s: float = 0.0,
    budget_s: float = 0.0,
    ckpt_dir: str = "",
    env: Optional[dict] = None,
    stderr_path: str = "",
    on_spawn=None,
    on_attempt=None,
    _sleep=time.sleep,
    _clock=time.monotonic,
) -> dict:
    """Run ``argv`` under restart-and-resume supervision; returns the
    lineage record (``kind="supervise_lineage"``).

    Restart policy per child exit:

    - ``0``  — done; stop with success.
    - ``17`` (:data:`WEDGED_EXIT_CODE`) — the child's watchdog declared the
      device wedged; restart (a fresh process re-leases the backend).
    - timeout (``attempt_timeout_s``) — the child never even reached its
      own watchdog (init wedge); kill and restart, counted as a wedge.
    - any other nonzero — restart when ``restart_on_crash`` else stop.

    Each restart sleeps ``min(backoff_s * backoff_factor**k, backoff_max_s)``
    first.  The child inherits the environment plus ``env`` plus
    ``DGRAPH_CHAOS_ATTEMPT=<attempt>``.

    ``budget_s`` (0 = none) is an overall fail-fast wall budget across
    attempts: once elapsed + the next backoff would cross it, the
    supervisor stops restarting (``budget_exhausted`` in the lineage)
    instead of burning its whole restart budget against a wedge — the
    bench probe phase runs through here with ``--probe-budget-s`` as
    this budget (ROADMAP item 5), and each attempt's timeout is clamped
    to the remaining window.  Attempt 0 always runs (>= 1 s).

    ``on_spawn(proc)`` is called with each child's ``Popen`` the moment
    it exists (bench's SIGTERM handler kills the in-flight probe through
    it); ``on_attempt(record)`` after each attempt resolves, with that
    attempt's lineage record (live probe-history logging).  Both default
    to no-ops and must not raise.

    ``stderr_path`` (default "": inherit) redirects each child's stderr
    to that file, truncated per attempt — so a child that dies in native
    code (segfault, PJRT abort) still leaves a diagnosable tail for the
    caller's failure record (bench's probe notes read it).

    ``_sleep``/``_clock`` are injectable (monotonic) so tests pin the
    exact backoff/cap/budget-clamp schedule with a fake clock — no real
    sleeps in tier-1.
    """
    if "_dgraph_obs_health" in sys.modules:  # standalone (bench supervisor)
        RunHealth = sys.modules["_dgraph_obs_health"].RunHealth
    else:
        from dgraph_tpu.obs.health import RunHealth

    # ONE trace per supervised run, one span per attempt: the restart
    # chain becomes a single timeline, and the children join it via the
    # exported trace env (obs.spans.child_env) — so their step metrics and
    # health records are joinable against this lineage by trace_id.
    run_span = spans.span("train.supervise", cmd=" ".join(argv))
    health = RunHealth.begin("train.supervisor")
    attempts = []
    rc: Optional[int] = None
    gave_up = False
    budget_exhausted = False
    t_start = _clock()
    for attempt in range(max_restarts + 1):
        delay = _backoff_delay(attempt, backoff_s, backoff_factor,
                               backoff_max_s)
        if attempt:
            if budget_s and (
                _clock() - t_start + delay >= budget_s
            ):
                gave_up = budget_exhausted = True
                break
            _sleep(delay)
        resume_step = _latest_step(ckpt_dir)
        attempt_span = spans.span(
            "supervise.attempt", parent=run_span,
            attempt=attempt, resume_step=resume_step,
        )
        child_env = {
            **os.environ, **(env or {}), ATTEMPT_ENV_VAR: str(attempt),
            **spans.child_env(parent=attempt_span),
        }
        # clamp the attempt timeout to the remaining budget window so one
        # wedged child cannot blow past the overall fail-fast budget
        # (attempt 0 always gets >= 1 s even under a tiny budget)
        timeout = attempt_timeout_s or 0.0
        if budget_s:
            remaining = max(budget_s - (_clock() - t_start), 1.0)
            timeout = min(timeout, remaining) if timeout else remaining
        t0 = _clock()
        timed_out = False
        # truncate-per-attempt so the file always holds the LAST
        # attempt's stderr — native crashes (segfault/PJRT abort) write
        # nothing anywhere else, and the caller's failure record must be
        # diagnosable without the console scrollback
        stderr_fh = open(stderr_path, "wb") if stderr_path else None
        try:
            proc = subprocess.Popen(argv, env=child_env, stderr=stderr_fh)
            if on_spawn is not None:
                on_spawn(proc)
            try:
                rc = proc.wait(timeout=timeout or None)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                timed_out = True
                rc = WEDGED_EXIT_CODE  # never reached its own watchdog: a wedge
        finally:
            if stderr_fh is not None:
                stderr_fh.close()
        wall_s = _clock() - t0
        if rc == 0:
            outcome = "ok"
        elif timed_out:
            outcome = "timeout"
        elif rc == WEDGED_EXIT_CODE:
            outcome = "wedged"
        else:
            outcome = "crashed"
        attempt_span.end(
            error=None if rc == 0 else f"exit {rc} ({outcome})",
            exit_code=rc, outcome=outcome,
        )
        attempts.append(
            {
                "attempt": attempt,
                "exit_code": rc,
                "outcome": outcome,
                "wall_s": round(wall_s, 3),
                "resume_step": resume_step,
                "backoff_s": round(delay, 3),
                # joinable against the span JSONL (None when tracing off)
                "span_id": attempt_span.span_id,
            }
        )
        if on_attempt is not None:
            on_attempt(attempts[-1])
        health.record_probe(
            attempt, wall_s,
            "ok" if rc == 0 else ("hang" if outcome in ("wedged", "timeout")
                                  else "error"),
            f"exit {rc} ({outcome}), resumed from {resume_step}",
        )
        if rc == 0:
            break
        if outcome == "crashed" and not restart_on_crash:
            break
        if attempt == max_restarts:
            gave_up = True
    restarts = len(attempts) - 1
    error, wedge = _final_error(
        rc, attempts[-1]["outcome"] if attempts else "never_ran", restarts,
        max_restarts=max_restarts, budget_s=budget_s,
        budget_exhausted=budget_exhausted, gave_up=gave_up,
    )
    run_span.end(error=error, restarts=restarts, final_exit_code=rc)
    return {
        "kind": "supervise_lineage",
        "cmd": list(argv),
        # the join key: every attempt span, child health record, and child
        # step-metrics line carries this id when tracing is on
        "trace_id": spans.current_trace_id(),
        "attempts": attempts,
        "restarts": restarts,
        "final_exit_code": rc,
        "gave_up": gave_up,
        "budget_exhausted": budget_exhausted,
        "final_step": _latest_step(ckpt_dir),
        "run_health": health.finish(error, wedge),
    }


def _rank_stderr_path(template: str, rank: int) -> str:
    """Per-rank stderr file from a template: ``{rank}`` substituted when
    present, else ``.rank<r>`` appended."""
    if not template:
        return ""
    if "{rank}" in template:
        return template.format(rank=rank)
    return f"{template}.rank{rank}"


def supervise_group(
    argv_for_rank,
    world_size: int,
    *,
    max_restarts: int = 8,
    backoff_s: float = 1.0,
    backoff_factor: float = 2.0,
    backoff_max_s: float = 60.0,
    restart_on_crash: bool = True,
    attempt_timeout_s: float = 0.0,
    budget_s: float = 0.0,
    rank_loss_grace_s: float = 30.0,
    min_world: int = 1,
    on_rank_loss=None,
    on_rank_join=None,
    resume_step_fn=None,
    ckpt_dir: str = "",
    env: Optional[dict] = None,
    rank_env: Optional[dict] = None,
    stderr_path: str = "",
    on_spawn=None,
    on_attempt=None,
    _sleep=time.sleep,
    _clock=time.monotonic,
    poll_interval_s: float = 0.05,
) -> dict:
    """Multi-rank supervision: one child per rank, one lineage per rank
    child, collective restart semantics, and a shrink-to-fit path on rank
    loss.  Returns the group lineage (``kind="supervise_group_lineage"``).

    ``argv_for_rank(rank, world_size, attempt)`` builds each child's argv
    — ranks are re-numbered ``0..W'-1`` after a shrink, so the callable is
    re-consulted every attempt.  Each child inherits the environment plus
    ``env`` plus its row of ``rank_env`` plus ``DGRAPH_CHAOS_ATTEMPT``,
    ``DGRAPH_RANK`` and ``DGRAPH_WORLD_SIZE``.

    Group restart policy, per attempt:

    - every rank exits 0 — done.
    - any rank exits ``17`` (:data:`WEDGED_EXIT_CODE`) or the attempt
      times out — **collective restart**: the surviving children are
      killed (outcome ``aborted``) and the whole group relaunches at the
      SAME world size after backoff (a wedge is a device/lease problem,
      not a membership change).
    - a rank **crashes** (killed, segfault, any other nonzero): the group
      is given ``rank_loss_grace_s`` for the survivors to detect the loss
      through membership (:mod:`dgraph_tpu.comm.membership`), checkpoint,
      and exit :data:`RANK_LOST_EXIT_CODE` (19).  If at least one survivor
      did, the crashed ranks are declared LOST: ``on_rank_loss(lost,
      world_size)`` runs the recovery (shrink-to-fit re-plan + checkpoint
      reshard — :func:`dgraph_tpu.train.shrink.shrink_world`) and returns
      the new world size; the group relaunches at ``W - len(lost)`` with
      ranks renumbered.  With no 19 exits it is a plain crash: restart at
      the same world size while ``restart_on_crash`` holds.
    - ``on_rank_loss=None`` (or a shrink below ``min_world``) stops the
      group with the rank-loss exit code instead of shrinking.
    - ranks exit :data:`RANK_JOIN_EXIT_CODE` (23) after observing a
      join announcement (:class:`~dgraph_tpu.comm.membership.Joiner`):
      the symmetric GROW path.  The same grace window lets the rest of
      the group observe, checkpoint, and exit 23; once every live rank
      reported, ``on_rank_join(world_size, attempt)`` runs the grow-to-
      fit transition (re-plan + checkpoint reshard + grant —
      :func:`dgraph_tpu.train.grow.grow_world`) and returns the new
      world size; the group relaunches at ``W + k`` with ranks
      renumbered.  ``on_rank_join=None`` stops the group with the
      rank-join exit code instead of growing.  Loss outranks arrival
      when both land in one attempt — the world must shrink to a
      consistent cut before it can entertain newcomers.

    ``budget_s`` is the SHARED fail-fast wall budget across every rank and
    attempt (the single-mode contract); per-attempt timeouts are clamped
    to the remaining window.  ``stderr_path`` is a per-rank template
    (``{rank}`` substituted, else ``.rank<r>`` appended), truncated per
    attempt like the single-rank capture.

    Watchdog/lease ordering matters: children should keep their
    ``step_deadline_s`` *below* the membership ``lease_s`` so a wedged
    rank exits 17 (collective restart, same world) before its peers give
    up on it and trigger a shrink.
    """
    if "_dgraph_obs_health" in sys.modules:  # standalone (bench supervisor)
        RunHealth = sys.modules["_dgraph_obs_health"].RunHealth
    else:
        from dgraph_tpu.obs.health import RunHealth

    run_span = spans.span("train.supervise_group", world_size=world_size)
    health = RunHealth.begin("train.supervisor.group")
    W = int(world_size)
    attempts: list = []
    shrinks: list = []
    grows: list = []
    rc: Optional[int] = None
    gave_up = False
    budget_exhausted = False
    stopped_on_loss = False
    stopped_on_join = False
    t_start = _clock()
    for attempt in range(max_restarts + 1):
        delay = _backoff_delay(attempt, backoff_s, backoff_factor,
                               backoff_max_s)
        if attempt:
            if budget_s and (_clock() - t_start + delay >= budget_s):
                gave_up = budget_exhausted = True
                break
            _sleep(delay)
        resume_step = (
            resume_step_fn(attempt, W) if resume_step_fn is not None
            else _latest_step(ckpt_dir)
        )
        attempt_span = spans.span(
            "supervise.group_attempt", parent=run_span,
            attempt=attempt, world_size=W, resume_step=resume_step,
        )
        timeout = attempt_timeout_s or 0.0
        if budget_s:
            remaining = max(budget_s - (_clock() - t_start), 1.0)
            timeout = min(timeout, remaining) if timeout else remaining
        t0 = _clock()
        procs: dict = {}
        stderr_fhs: dict = {}
        rank_spans: dict = {}
        try:
            try:
                for r in range(W):
                    child_env = {
                        **os.environ, **(env or {}),
                        **((rank_env or {}).get(r) or {}),
                        ATTEMPT_ENV_VAR: str(attempt),
                        RANK_ENV_VAR: str(r),
                        "DGRAPH_WORLD_SIZE": str(W),
                        **spans.child_env(parent=attempt_span),
                    }
                    sp = _rank_stderr_path(stderr_path, r)
                    fh = open(sp, "wb") if sp else None
                    stderr_fhs[r] = fh
                    rank_spans[r] = spans.span(
                        "supervise.rank", parent=attempt_span,
                        rank=r, attempt=attempt,
                    )
                    procs[r] = subprocess.Popen(
                        argv_for_rank(r, W, attempt), env=child_env,
                        stderr=fh,
                    )
                    if on_spawn is not None:
                        on_spawn(procs[r])
            except BaseException:
                # a failed rank-K spawn must not orphan ranks 0..K-1: no
                # supervisor would ever wait or kill them
                for p in procs.values():
                    try:
                        p.kill()
                        p.wait()
                    except OSError:
                        pass
                raise
            # --- monitor: collective-restart on wedge, grace on crash ---
            exit_codes: dict = {}
            ends: dict = {}
            aborted: set = set()
            timed_out = False
            grace_deadline = None
            while len(exit_codes) < W:
                for r, p in procs.items():
                    if r in exit_codes:
                        continue
                    code = p.poll()
                    if code is not None:
                        exit_codes[r] = code
                        ends[r] = _clock()
                now = _clock()
                live = [r for r in procs if r not in exit_codes]
                if not live:
                    break
                if timeout and now - t0 > timeout:
                    timed_out = True
                elif any(
                    c == WEDGED_EXIT_CODE for c in exit_codes.values()
                ):
                    # one wedged rank restarts the WHOLE group: its peers
                    # would only burn their halo-exchange deadlines —
                    # fall through to the kill below
                    pass
                else:
                    # a CRASH starts the grace window: survivors get time
                    # to DETECT the loss (membership lease), checkpoint,
                    # and exit 19.  19-reporters themselves start it only
                    # as a QUORUM of what's left — that covers the zombie
                    # (a rank whose process is alive but whose lease
                    # expired: every peer exits 19 and waiting on the
                    # zombie forever would hang the shrink they asked
                    # for) without letting ONE spurious detection abort a
                    # healthy still-training group
                    crashed_now = [
                        r for r, c in exit_codes.items()
                        if c not in (0, WEDGED_EXIT_CODE,
                                     RANK_LOST_EXIT_CODE,
                                     RANK_JOIN_EXIT_CODE)
                    ]
                    reporters = [
                        r for r, c in exit_codes.items()
                        if c == RANK_LOST_EXIT_CODE
                    ]
                    # 23-reporters (observed a join announcement) share
                    # the loss quorum rule: the first reporter starts the
                    # grace window only as a quorum of what's left, so
                    # the rest of the group gets time to observe the same
                    # join, checkpoint, and exit 23 — without one early
                    # observer aborting a healthy still-training group
                    join_reporters = [
                        r for r, c in exit_codes.items()
                        if c == RANK_JOIN_EXIT_CODE
                    ]
                    if grace_deadline is None and (
                        crashed_now
                        or (reporters and len(reporters) >= len(live))
                        or (join_reporters
                            and len(join_reporters) >= len(live))
                    ):
                        grace_deadline = now + rank_loss_grace_s
                    if grace_deadline is None or now < grace_deadline:
                        _sleep(poll_interval_s)
                        continue
                # timeout / wedge / grace expiry: kill the stragglers
                for r in live:
                    procs[r].kill()
                    procs[r].wait()
                    exit_codes[r] = procs[r].returncode
                    ends[r] = _clock()
                    aborted.add(r)
                break
        finally:
            for fh in stderr_fhs.values():
                if fh is not None:
                    fh.close()
        # --- classify ranks + the group ---
        rank_recs = []
        for r in range(W):
            code = exit_codes.get(r)
            if r in aborted:
                outcome = "timeout" if timed_out else "aborted"
            elif code == 0:
                outcome = "ok"
            elif code == WEDGED_EXIT_CODE:
                outcome = "wedged"
            elif code == RANK_LOST_EXIT_CODE:
                outcome = "rank_lost"
            elif code == RANK_JOIN_EXIT_CODE:
                outcome = "rank_join"
            else:
                outcome = "crashed"
            rank_spans[r].end(
                error=None if code == 0 else f"exit {code} ({outcome})",
                exit_code=code, outcome=outcome,
            )
            rank_recs.append({
                "rank": r,
                "exit_code": code,
                "outcome": outcome,
                "wall_s": round(ends.get(r, _clock()) - t0, 3),
                "span_id": rank_spans[r].span_id,
            })
        outcomes = {rec["outcome"] for rec in rank_recs}
        # the LOST set: ranks that crashed, plus ranks the grace expiry
        # killed (zombies whose peers declared them lost and exited 19 —
        # their processes outlived their leases)
        dead = sorted(
            rec["rank"] for rec in rank_recs
            if rec["outcome"] in ("crashed", "aborted")
        )
        if outcomes == {"ok"}:
            group_outcome, rc = "ok", 0
        elif timed_out:
            group_outcome = "timeout"
            rc = WEDGED_EXIT_CODE
        elif "wedged" in outcomes:
            group_outcome = "wedged"
            rc = WEDGED_EXIT_CODE
        elif "rank_lost" in outcomes and dead:
            group_outcome = "rank_lost"
            rc = RANK_LOST_EXIT_CODE
        elif dead:
            group_outcome = "crashed"
            # the CRASHING rank's code, not a grace-expiry kill signal —
            # the operator (and anything keying on exit status) needs the
            # real failure, and aborted survivors only died because of it
            crashed_codes = [
                rec["exit_code"] for rec in rank_recs
                if rec["outcome"] == "crashed"
            ]
            rc = crashed_codes[0] if crashed_codes else next(
                rec["exit_code"] for rec in rank_recs
                if rec["exit_code"] not in (0, None)
            )
        elif "rank_join" in outcomes and "rank_lost" not in outcomes:
            # every live rank observed the join announcement and exited
            # 23 cleanly: the grow path. A simultaneous loss report
            # falls through to the crashed ladder below instead — the
            # world must shrink to a consistent cut before it grows
            group_outcome = "rank_join"
            rc = RANK_JOIN_EXIT_CODE
        else:  # only ok + rank_lost reporters, nobody actually died
            group_outcome = "crashed"
            rc = RANK_LOST_EXIT_CODE
        attempt_rec = {
            "attempt": attempt,
            "world_size": W,
            "outcome": group_outcome,
            "backoff_s": round(delay, 3),
            "wall_s": round(_clock() - t0, 3),
            "resume_step": resume_step,
            "ranks": rank_recs,
            "shrink": None,
            "grow": None,
            "span_id": attempt_span.span_id,
        }
        attempt_span.end(
            error=None if rc == 0 else f"group {group_outcome}",
            outcome=group_outcome,
        )
        attempts.append(attempt_rec)
        health.record_probe(
            attempt, attempt_rec["wall_s"],
            "ok" if rc == 0 else (
                "hang" if group_outcome in ("wedged", "timeout") else "error"
            ),
            f"group {group_outcome} at W={W}, resumed from {resume_step}",
        )
        if on_attempt is not None:
            on_attempt(attempt_rec)
        if rc == 0:
            break
        if group_outcome == "rank_lost":
            if attempt == max_restarts:
                # no restart budget left to LAUNCH a shrunk world: don't
                # burn the re-plan/reshard on a result nobody would run
                gave_up = True
                break
            new_world = W - len(dead)
            if on_rank_loss is None or new_world < min_world:
                stopped_on_loss = True
                break
            shrink_rec = {
                "attempt": attempt,
                "lost": dead,
                "old_world": W,
                "new_world": new_world,
            }
            with spans.span(
                "supervise.shrink", parent=run_span, **shrink_rec
            ):
                got = on_rank_loss(dead, W)
            if got is not None:
                new_world = int(got)
            if new_world < min_world:
                stopped_on_loss = True
                break
            shrink_rec["new_world"] = new_world
            attempt_rec["shrink"] = shrink_rec
            shrinks.append(shrink_rec)
            health.record_event({"kind": "shrink", **shrink_rec})
            W = new_world
            continue
        if group_outcome == "rank_join":
            if attempt == max_restarts:
                # no restart budget left to LAUNCH a grown world: don't
                # burn the re-plan/reshard on a result nobody would run
                gave_up = True
                break
            if on_rank_join is None:
                stopped_on_join = True
                break
            grow_rec = {"attempt": attempt, "old_world": W}
            with spans.span(
                "supervise.grow", parent=run_span, **grow_rec
            ):
                got = on_rank_join(W, attempt)
            if got is None or int(got) <= W:
                # the callback declined (stale announcement, quota, ...):
                # nothing grew, so a relaunch at the same world would
                # just re-observe the join and loop — stop instead
                stopped_on_join = True
                break
            new_world = int(got)
            grow_rec["new_world"] = new_world
            attempt_rec["grow"] = grow_rec
            grows.append(grow_rec)
            health.record_event({"kind": "grow", **grow_rec})
            W = new_world
            continue
        if group_outcome == "crashed" and not restart_on_crash:
            break
        if attempt == max_restarts:
            gave_up = True
    restarts = len(attempts) - 1
    error, wedge = _final_error(
        rc, attempts[-1]["outcome"] if attempts else "never_ran", restarts,
        max_restarts=max_restarts, budget_s=budget_s,
        budget_exhausted=budget_exhausted, gave_up=gave_up,
        stopped_on_loss=stopped_on_loss, stopped_on_join=stopped_on_join,
        what="group",
    )
    run_span.end(
        error=error, restarts=restarts, final_exit_code=rc,
        final_world_size=W,
    )
    return {
        "kind": "supervise_group_lineage",
        "world_size": int(world_size),
        "final_world_size": W,
        "trace_id": spans.current_trace_id(),
        "attempts": attempts,
        "restarts": restarts,
        "shrinks": shrinks,
        "grows": grows,
        "final_exit_code": rc,
        "gave_up": gave_up,
        "budget_exhausted": budget_exhausted,
        "stopped_on_rank_loss": stopped_on_loss,
        "stopped_on_rank_join": stopped_on_join,
        "final_step": _latest_step(ckpt_dir),
        "run_health": health.finish(error, wedge),
    }


def main(cfg: Config) -> dict:
    if not cfg.cmd.strip():
        raise SystemExit(
            'supervise: --cmd is required, e.g. --cmd "python -m '
            'experiments.ogb_gcn --epochs 100"'
        )
    if cfg.ranks > 0:
        # substitute ONLY the documented placeholders (str.format would
        # crash on any other literal brace in the command line — JSON
        # args, glob patterns — and the same cmd must behave identically
        # with and without --ranks)
        def argv_for_rank(r, w, _attempt):
            return shlex.split(
                cfg.cmd.replace("{rank}", str(r)).replace("{world}", str(w))
            )

        lineage = supervise_group(
            argv_for_rank,
            cfg.ranks,
            max_restarts=cfg.max_restarts,
            backoff_s=cfg.backoff_s,
            backoff_factor=cfg.backoff_factor,
            backoff_max_s=cfg.backoff_max_s,
            restart_on_crash=cfg.restart_on_crash,
            attempt_timeout_s=cfg.attempt_timeout_s,
            budget_s=cfg.budget_s,
            rank_loss_grace_s=cfg.rank_loss_grace_s,
            stderr_path=cfg.stderr_path,
            ckpt_dir=cfg.ckpt_dir,
        )
        _append_jsonl(cfg.log_path, lineage)
        _ledger_ingest(lineage)
        print(json.dumps(lineage, indent=cfg.indent or None), flush=True)
        if lineage["final_exit_code"] != 0:
            sys.exit(lineage["final_exit_code"])
        return lineage
    argv = shlex.split(cfg.cmd)
    lineage = supervise(
        argv,
        max_restarts=cfg.max_restarts,
        backoff_s=cfg.backoff_s,
        backoff_factor=cfg.backoff_factor,
        backoff_max_s=cfg.backoff_max_s,
        restart_on_crash=cfg.restart_on_crash,
        attempt_timeout_s=cfg.attempt_timeout_s,
        budget_s=cfg.budget_s,
        stderr_path=cfg.stderr_path,
        ckpt_dir=cfg.ckpt_dir,
    )
    _append_jsonl(cfg.log_path, lineage)
    _ledger_ingest(lineage)
    # the lineage is ALWAYS the last stdout line, parseable on every exit
    # path (the bench-supervisor contract pinned by tests)
    print(json.dumps(lineage, indent=cfg.indent or None), flush=True)
    if lineage["final_exit_code"] != 0:
        sys.exit(lineage["final_exit_code"])
    return lineage


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    try:
        main(parse_config(Config))
    except SystemExit:
        raise
    except BaseException as e:
        # an unexpected supervisor bug must not cost the lineage JSON
        from dgraph_tpu.obs.health import RunHealth

        h = RunHealth.begin("train.supervisor")
        print(
            json.dumps(
                {
                    "kind": "supervise_lineage",
                    "attempts": [],
                    "restarts": 0,
                    "final_exit_code": None,
                    "gave_up": False,
                    "budget_exhausted": False,
                    "run_health": h.finish(
                        f"supervisor crashed: {type(e).__name__}: {e}",
                        "stage_failure",
                    ),
                }
            ),
            flush=True,
        )
        sys.exit(70)
