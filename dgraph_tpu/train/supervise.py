"""Self-healing train supervisor: restart-and-resume around exit 17.

``train/elastic.py`` documents the restart contract — a wedged device makes
:class:`~dgraph_tpu.train.elastic.StepWatchdog` hard-exit the process with
:data:`~dgraph_tpu.train.elastic.WEDGED_EXIT_CODE` (17), and "the launcher
treats that exit as restart-and-resume" — but until this module the repo
shipped no launcher.  ``python -m dgraph_tpu.train.supervise`` is it:

- runs the training entrypoint as a subprocess;
- restarts it on exit 17 (wedge), on crash (any nonzero exit, optional),
  and on an attempt-level wall timeout, with exponential backoff and a
  max-restart budget;
- resumption is the child's job (restore ``latest_step()`` from its
  checkpoint dir); the supervisor reads the same ``latest_step()`` before
  each attempt so the lineage records what each attempt resumed from;
- exports the attempt ordinal as ``DGRAPH_CHAOS_ATTEMPT`` so a chaos
  clause (:mod:`dgraph_tpu.chaos`) can target exactly one attempt — the
  end-to-end recovery test injects a wedge on attempt 0 and proves the
  resumed run is bit-identical to a fault-free one;
- emits ONE JSON-parseable lineage record on EVERY exit path (the bench
  supervisor's discipline): attempt count, per-attempt exit codes and
  wall times, resume steps, and a RunHealth record.

The supervisor itself never touches the accelerator: reading
``latest_step`` is a directory listing, and no jax API is called — a
wedged lease can hang a child, never the process that must outlive it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Optional

# bench.py's wedge-surviving supervisor loads this file STANDALONE (by
# path, registered as ``_dgraph_train_supervise``) so its backend-probe
# loop can run under this exact restart/backoff/budget policy without
# importing the dgraph_tpu package — whose ``__init__`` imports jax (the
# same contract obs/health.py and obs/spans.py carry).  The spans twin is
# registered in sys.modules before this module is exec'd; the literal
# fallbacks are the canonical contract values, pinned against the package
# ones in tests/test_plan_shards.py.  Keyed on OUR module name so a
# normal package import never takes this branch, even in a process that
# also loaded bench's standalone twins.
if __name__ == "_dgraph_train_supervise":  # standalone (bench supervisor)
    spans = sys.modules["_dgraph_obs_spans"]
    WEDGED_EXIT_CODE = 17  # train.elastic.WEDGED_EXIT_CODE
    ATTEMPT_ENV_VAR = "DGRAPH_CHAOS_ATTEMPT"  # chaos.ATTEMPT_ENV_VAR
else:
    import dgraph_tpu.obs.spans as spans  # jax-free (lint-enforced)
    from dgraph_tpu.chaos import ATTEMPT_ENV_VAR
    from dgraph_tpu.train.elastic import WEDGED_EXIT_CODE


@dataclasses.dataclass
class Config:
    """Train supervisor (``--cmd "python -m ..."`` is the child entrypoint;
    restarts on exit 17/crash with exponential backoff)."""

    cmd: str = ""  # shell-style child command line (shlex-split)
    max_restarts: int = 8  # restart budget (attempts = budget + 1)
    backoff_s: float = 1.0  # first restart delay
    backoff_factor: float = 2.0
    backoff_max_s: float = 60.0
    restart_on_crash: bool = True  # False: only exit 17 restarts
    attempt_timeout_s: float = 0.0  # 0 = none; kill + restart past this
    budget_s: float = 0.0  # 0 = none; overall fail-fast wall budget
    stderr_path: str = ""  # capture child stderr here (truncated/attempt)
    ckpt_dir: str = ""  # lineage: record latest_step() resume points
    log_path: str = "logs/supervise.jsonl"
    indent: int = 0


def _latest_step(ckpt_dir: str) -> Optional[int]:
    """latest_step without importing the checkpoint module's orbax path at
    module import time (it is jax-free, but keep the supervisor's import
    surface minimal and explicit)."""
    if not ckpt_dir:
        return None
    from dgraph_tpu.train.checkpoint import latest_step

    return latest_step(ckpt_dir)


def _append_jsonl(path: str, rec: dict) -> None:
    """Plain JSONL append — ExperimentLog calls ``jax.process_index()``
    (backend init), which the supervisor must never do."""
    if not path:
        return
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as fh:
        fh.write(json.dumps(rec) + "\n")


def supervise(
    argv: list,
    *,
    max_restarts: int = 8,
    backoff_s: float = 1.0,
    backoff_factor: float = 2.0,
    backoff_max_s: float = 60.0,
    restart_on_crash: bool = True,
    attempt_timeout_s: float = 0.0,
    budget_s: float = 0.0,
    ckpt_dir: str = "",
    env: Optional[dict] = None,
    stderr_path: str = "",
    on_spawn=None,
    on_attempt=None,
    _sleep=time.sleep,
) -> dict:
    """Run ``argv`` under restart-and-resume supervision; returns the
    lineage record (``kind="supervise_lineage"``).

    Restart policy per child exit:

    - ``0``  — done; stop with success.
    - ``17`` (:data:`WEDGED_EXIT_CODE`) — the child's watchdog declared the
      device wedged; restart (a fresh process re-leases the backend).
    - timeout (``attempt_timeout_s``) — the child never even reached its
      own watchdog (init wedge); kill and restart, counted as a wedge.
    - any other nonzero — restart when ``restart_on_crash`` else stop.

    Each restart sleeps ``min(backoff_s * backoff_factor**k, backoff_max_s)``
    first.  The child inherits the environment plus ``env`` plus
    ``DGRAPH_CHAOS_ATTEMPT=<attempt>``.

    ``budget_s`` (0 = none) is an overall fail-fast wall budget across
    attempts: once elapsed + the next backoff would cross it, the
    supervisor stops restarting (``budget_exhausted`` in the lineage)
    instead of burning its whole restart budget against a wedge — the
    bench probe phase runs through here with ``--probe-budget-s`` as
    this budget (ROADMAP item 5), and each attempt's timeout is clamped
    to the remaining window.  Attempt 0 always runs (>= 1 s).

    ``on_spawn(proc)`` is called with each child's ``Popen`` the moment
    it exists (bench's SIGTERM handler kills the in-flight probe through
    it); ``on_attempt(record)`` after each attempt resolves, with that
    attempt's lineage record (live probe-history logging).  Both default
    to no-ops and must not raise.

    ``stderr_path`` (default "": inherit) redirects each child's stderr
    to that file, truncated per attempt — so a child that dies in native
    code (segfault, PJRT abort) still leaves a diagnosable tail for the
    caller's failure record (bench's probe notes read it).
    """
    if "_dgraph_obs_health" in sys.modules:  # standalone (bench supervisor)
        RunHealth = sys.modules["_dgraph_obs_health"].RunHealth
    else:
        from dgraph_tpu.obs.health import RunHealth

    # ONE trace per supervised run, one span per attempt: the restart
    # chain becomes a single timeline, and the children join it via the
    # exported trace env (obs.spans.child_env) — so their step metrics and
    # health records are joinable against this lineage by trace_id.
    run_span = spans.span("train.supervise", cmd=" ".join(argv))
    health = RunHealth.begin("train.supervisor")
    attempts = []
    rc: Optional[int] = None
    gave_up = False
    budget_exhausted = False
    t_start = time.monotonic()
    for attempt in range(max_restarts + 1):
        if attempt:
            delay = min(
                backoff_s * backoff_factor ** (attempt - 1), backoff_max_s
            )
            if budget_s and (
                time.monotonic() - t_start + delay >= budget_s
            ):
                gave_up = budget_exhausted = True
                break
            _sleep(delay)
        else:
            delay = 0.0
        resume_step = _latest_step(ckpt_dir)
        attempt_span = spans.span(
            "supervise.attempt", parent=run_span,
            attempt=attempt, resume_step=resume_step,
        )
        child_env = {
            **os.environ, **(env or {}), ATTEMPT_ENV_VAR: str(attempt),
            **spans.child_env(parent=attempt_span),
        }
        # clamp the attempt timeout to the remaining budget window so one
        # wedged child cannot blow past the overall fail-fast budget
        # (attempt 0 always gets >= 1 s even under a tiny budget)
        timeout = attempt_timeout_s or 0.0
        if budget_s:
            remaining = max(budget_s - (time.monotonic() - t_start), 1.0)
            timeout = min(timeout, remaining) if timeout else remaining
        t0 = time.monotonic()
        timed_out = False
        # truncate-per-attempt so the file always holds the LAST
        # attempt's stderr — native crashes (segfault/PJRT abort) write
        # nothing anywhere else, and the caller's failure record must be
        # diagnosable without the console scrollback
        stderr_fh = open(stderr_path, "wb") if stderr_path else None
        try:
            proc = subprocess.Popen(argv, env=child_env, stderr=stderr_fh)
            if on_spawn is not None:
                on_spawn(proc)
            try:
                rc = proc.wait(timeout=timeout or None)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
                timed_out = True
                rc = WEDGED_EXIT_CODE  # never reached its own watchdog: a wedge
        finally:
            if stderr_fh is not None:
                stderr_fh.close()
        wall_s = time.monotonic() - t0
        if rc == 0:
            outcome = "ok"
        elif timed_out:
            outcome = "timeout"
        elif rc == WEDGED_EXIT_CODE:
            outcome = "wedged"
        else:
            outcome = "crashed"
        attempt_span.end(
            error=None if rc == 0 else f"exit {rc} ({outcome})",
            exit_code=rc, outcome=outcome,
        )
        attempts.append(
            {
                "attempt": attempt,
                "exit_code": rc,
                "outcome": outcome,
                "wall_s": round(wall_s, 3),
                "resume_step": resume_step,
                "backoff_s": round(delay, 3),
                # joinable against the span JSONL (None when tracing off)
                "span_id": attempt_span.span_id,
            }
        )
        if on_attempt is not None:
            on_attempt(attempts[-1])
        health.record_probe(
            attempt, wall_s,
            "ok" if rc == 0 else ("hang" if outcome in ("wedged", "timeout")
                                  else "error"),
            f"exit {rc} ({outcome}), resumed from {resume_step}",
        )
        if rc == 0:
            break
        if outcome == "crashed" and not restart_on_crash:
            break
        if attempt == max_restarts:
            gave_up = True
    restarts = len(attempts) - 1
    if rc == 0:
        error, wedge = None, None
    else:
        last = attempts[-1]["outcome"]
        if budget_exhausted:
            exhausted = f"; wall budget ({budget_s:g}s) exhausted"
        elif gave_up:
            exhausted = f"; restart budget ({max_restarts}) exhausted"
        else:
            exhausted = ""
        error = (
            f"child exited {rc} ({last}) after {restarts} restart(s)"
            + exhausted
        )
        wedge = (
            "watchdog_timeout" if last in ("wedged", "timeout")
            else "stage_failure"
        )
    run_span.end(error=error, restarts=restarts, final_exit_code=rc)
    return {
        "kind": "supervise_lineage",
        "cmd": list(argv),
        # the join key: every attempt span, child health record, and child
        # step-metrics line carries this id when tracing is on
        "trace_id": spans.current_trace_id(),
        "attempts": attempts,
        "restarts": restarts,
        "final_exit_code": rc,
        "gave_up": gave_up,
        "budget_exhausted": budget_exhausted,
        "final_step": _latest_step(ckpt_dir),
        "run_health": health.finish(error, wedge),
    }


def main(cfg: Config) -> dict:
    if not cfg.cmd.strip():
        raise SystemExit(
            'supervise: --cmd is required, e.g. --cmd "python -m '
            'experiments.ogb_gcn --epochs 100"'
        )
    argv = shlex.split(cfg.cmd)
    lineage = supervise(
        argv,
        max_restarts=cfg.max_restarts,
        backoff_s=cfg.backoff_s,
        backoff_factor=cfg.backoff_factor,
        backoff_max_s=cfg.backoff_max_s,
        restart_on_crash=cfg.restart_on_crash,
        attempt_timeout_s=cfg.attempt_timeout_s,
        budget_s=cfg.budget_s,
        stderr_path=cfg.stderr_path,
        ckpt_dir=cfg.ckpt_dir,
    )
    _append_jsonl(cfg.log_path, lineage)
    # the lineage is ALWAYS the last stdout line, parseable on every exit
    # path (the bench-supervisor contract pinned by tests)
    print(json.dumps(lineage, indent=cfg.indent or None), flush=True)
    if lineage["final_exit_code"] != 0:
        sys.exit(lineage["final_exit_code"])
    return lineage


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    try:
        main(parse_config(Config))
    except SystemExit:
        raise
    except BaseException as e:
        # an unexpected supervisor bug must not cost the lineage JSON
        from dgraph_tpu.obs.health import RunHealth

        h = RunHealth.begin("train.supervisor")
        print(
            json.dumps(
                {
                    "kind": "supervise_lineage",
                    "attempts": [],
                    "restarts": 0,
                    "final_exit_code": None,
                    "gave_up": False,
                    "budget_exhausted": False,
                    "run_health": h.finish(
                        f"supervisor crashed: {type(e).__name__}: {e}",
                        "stage_failure",
                    ),
                }
            ),
            flush=True,
        )
        sys.exit(70)
