"""Grow-to-fit elastic world expansion: re-plan, reshard, adopt.

The arrival mirror of :mod:`dgraph_tpu.train.shrink` — rank *arrival*
treated as a planned redistribution to a LARGER world ("Memory-efficient
array redistribution through portable collective communication",
PAPERS.md) instead of a restart-from-scratch.  Detection lives in
:mod:`dgraph_tpu.comm.membership` (the :class:`~dgraph_tpu.comm.
membership.Joiner` announcement + :class:`~dgraph_tpu.comm.membership.
JoinRequest` poll events); the restart policy in :func:`dgraph_tpu.train.
supervise.supervise_group`'s ``on_rank_join`` path; this module owns the
world-growth transition itself:

- **Same run directory, same generational artifacts.** A grow transition
  writes the NEXT generation of the exact layout shrink owns —
  ``plan_g<g>``, ``ckpt_g<g>/rank_<r>``, ``membership_g<g>_a<a>``,
  ``graph_g<g>.npz`` — and commits it with the same single atomic
  ``world.json`` pointer flip.  Grow and shrink transitions compose
  freely into one generation chain (g0 → grow → g1 → shrink → g2 ...),
  because every generation is self-describing and every reader derives
  its paths from the adopted pointer.

- **Grow = unfold + rebuild + reshard + atomic adopt.**
  :func:`grow_world` donates tail chunks of the existing ranks' blocks
  to the newcomers (:func:`~dgraph_tpu.partition.unfold_partition` —
  the deterministic waterfill inverse of ``fold_partition``; kept
  vertices never move), renumbers, and rebuilds the plan for W+k **in
  the background** through the streaming resumable builder
  (:func:`~dgraph_tpu.train.shrink.build_generation_plan`) while the
  foreground gathers the newest checkpoint step durable on EVERY old
  rank (the last consistent cut) and reshards it row-by-vertex-identity
  into W+k blocks.  Only after the new plan, checkpoints, and graph
  snapshot are all durable does ``world.json`` flip — a crash at ANY
  point leaves either the old world or the new world adopted, never a
  torn mix (``grow.replan`` / ``grow.adopt`` chaos points make both
  crash windows injectable).

- **Joiners are granted, never guessed.** New ranks ``W .. W+k-1`` are
  assigned to join tokens in sorted-token order (deterministic on
  rerun).  The grant files that tell each joiner its rank are written by
  the CALLER via :func:`grant_joined` AFTER :func:`grow_world` returns:
  the pointer flip must be the transition's last filesystem effect
  (host-pointer-flip-last), and a grant naming generation g+1 must never
  exist before the pointer that defines it.

- **Bit-identical expanded resume.** Every step is a pure function of
  ``(old artifacts, join tokens)``; a resumed grown run is bit-identical
  to a fault-free W+k run started from the same resharded checkpoint —
  the shrink contract run in reverse, pinned end-to-end by
  ``tests/test_grow.py``.

This module is lint-enforced jax-free (the grow decision path must keep
working while jax is wedged); everything that pulls jax — the plan
builder, the reshard kernel — is reached through :mod:`dgraph_tpu.train.
shrink`'s function-scope imports.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import numpy as np

import dgraph_tpu.obs.spans as spans
from dgraph_tpu import chaos

# submodule form, not `from dgraph_tpu.train import shrink`: naming the
# package would flag the jax-free lint (its __init__ pulls jax); the
# shrink module itself is the jax quarantine this module rides
import dgraph_tpu.train.shrink as shrink

_logger = logging.getLogger("dgraph_tpu.grow")


class GrowError(RuntimeError):
    """A world-growth transition could not complete (no pending joiners,
    no consistent checkpoint cut, missing generation artifacts, ...)."""

    def __init__(self, reason: str):
        super().__init__(f"grow-to-fit transition failed: {reason}")
        self.reason = reason

    def record(self) -> dict:
        return {"kind": "grow_error", "reason": self.reason}


def grow_record(rec: dict, replan_s: float = 0.0, shards: int = 0) -> dict:
    """The ``grow_transition`` ledger record for one adopted transition
    (:mod:`dgraph_tpu.obs.ledger` ingests it; ``obs.regress`` gates the
    world/shard counts byte-exact)."""
    last = (rec.get("join_history") or [{}])[-1]
    return {
        "kind": "grow_transition",
        "generation": int(rec.get("generation", 0)),
        "old_world": int(rec.get("world_size", 0)) - len(last.get("joined", {})),
        "new_world": int(rec.get("world_size", 0)),
        "resume_step": int(rec.get("resume_step", 0)),
        "joined": sorted(last.get("joined", {})),
        "replan_s": float(replan_s),
        "shards": int(shards),
    }


def grow_world(
    run_dir: str, tokens=None, *, attempt: int = 0,
) -> dict:
    """Transition the run to ``W + len(tokens)`` ranks; returns the
    adopted world record (plus ``resume_step`` and the token -> rank
    assignment in its ``join_history`` tail).

    ``tokens`` names the joiners; None discovers them from the live
    generation's membership directory (every pending
    :class:`~dgraph_tpu.comm.membership.Joiner` announcement for the
    current generation/``attempt``).  Crash-safe and rerunnable exactly
    like :func:`~dgraph_tpu.train.shrink.shrink_world`: artifacts are
    written under the NEW generation's names (the old world stays intact
    and adopted until the final pointer flip), the plan build resumes
    from its own manifest, and checkpoint/graph writes are atomic.  The
    plan rebuild runs in a background thread, overlapped with the
    checkpoint gather/reshard.
    """
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.comm.membership import read_joins
    from dgraph_tpu.partition import renumber_contiguous, unfold_partition
    from dgraph_tpu.train.checkpoint import (
        all_steps,
        restore_checkpoint,
        save_checkpoint,
    )

    world = shrink.read_world(run_dir)
    gen, W = int(world["generation"]), int(world["world_size"])
    if tokens is None:
        tokens = read_joins(
            shrink.membership_dir(run_dir, gen, attempt), generation=gen,
        )
    tokens = sorted(str(t) for t in tokens)
    if not tokens:
        raise GrowError(
            f"no pending join announcements for generation {gen} "
            f"(membership dir {shrink.membership_dir(run_dir, gen, attempt)})"
        )
    k = len(tokens)
    new_gen, new_world = gen + 1, W + k
    # deterministic on rerun: new ranks W..W+k-1 in sorted-token order
    joined = {t: W + i for i, t in enumerate(tokens)}
    with spans.span(
        "grow.recover", run_dir=run_dir, generation=new_gen,
        old_world=W, new_world=new_world, joined=tokens,
    ) as gspan:
        # a kill HERE (grow.replan=sigterm@0) leaves zero new-generation
        # artifacts: the old world stays adopted and untouched
        chaos.fire("grow.replan")
        graph = np.load(shrink.graph_path(run_dir, gen))
        part_unfold, donor_map = unfold_partition(graph["partition"], W, k)
        ren = renumber_contiguous(part_unfold, new_world)
        new_edges = ren.perm[np.asarray(graph["edge_index"])]
        orig_ids = np.asarray(graph["orig_ids"])[ren.inv]

        # background: rebuild the plan for the grown world through the
        # streaming per-rank builder (durable + resumable, plan.* chaos
        # points live) while the foreground reshards the checkpoint
        build_out: dict = {}

        def _build():
            t0 = time.monotonic()
            with spans.span("grow.replan", parent=gspan,
                            world_size=new_world):
                try:
                    build_out["manifest"] = shrink.build_generation_plan(
                        run_dir, new_gen, new_edges, ren.partition,
                        world, new_world,
                    )
                except BaseException as e:  # re-raised on join
                    build_out["error"] = e
            build_out["wall_s"] = time.monotonic() - t0

        builder = threading.Thread(target=_build, name="grow-replan")
        builder.start()

        # foreground: the newest checkpoint step durable on EVERY old
        # rank — the newcomers start from the old world's last consistent
        # cut, and a step some rank never finished saving is not one
        step_sets = [
            set(all_steps(shrink.rank_ckpt_dir(run_dir, gen, r)))
            for r in range(W)
        ]
        common = set.intersection(*step_sets) if step_sets else set()
        if not common:
            builder.join()
            raise GrowError(
                f"no checkpoint step durable on all {W} rank(s) of "
                f"generation {gen} (per-rank steps: "
                f"{[sorted(s) for s in step_sets]})"
            )
        resume_step = max(common)
        with spans.span("grow.gather", parent=gspan, step=resume_step):
            per_rank = [
                restore_checkpoint(
                    shrink.rank_ckpt_dir(run_dir, gen, r), step=resume_step
                )
                for r in range(W)
            ]
        builder.join()
        if "error" in build_out:
            raise build_out["error"]
        manifest = build_out["manifest"]
        statics = manifest["statics"]
        if not statics.get("homogeneous", True):
            raise NotImplementedError(
                "grow_world currently reshards homogeneous vertex state"
            )
        n_pad_new = int(statics["n_dst_pad"])
        old_statics = ps.read_manifest(shrink.plan_dir(run_dir, gen))["statics"]
        n_pad_old = int(old_statics["n_dst_pad"])

        with spans.span("grow.reshard", parent=gspan, step=resume_step):
            new_states = shrink._reshard_states(
                [p["state"] for p in per_rank],
                np.asarray(graph["counts"]),
                n_pad_old,
                ren.inv,
                ren.counts,
                n_pad_new,
                new_world,
            )
            for r in range(new_world):
                save_checkpoint(
                    shrink.rank_ckpt_dir(run_dir, new_gen, r),
                    {"state": new_states[r], "step": resume_step},
                    resume_step,
                )
        # atomic like the checkpoints above it: a torn snapshot under a
        # valid name would poison every later fold/unfold
        ps.atomic_savez(
            shrink.graph_path(run_dir, new_gen),
            edge_index=new_edges,
            partition=ren.partition,
            counts=ren.counts,
            orig_ids=orig_ids,
        )
        rec = {
            **world,
            "generation": new_gen,
            "world_size": new_world,
            "resume_step": int(resume_step),
            "join_history": list(world.get("join_history", []))
            + [{"generation": gen, "joined": joined,
                "donors": donor_map, "resume_step": int(resume_step)}],
        }
        # a kill HERE (grow.adopt=sigterm@0) is the torn-window
        # injection: every new-generation artifact is durable but the
        # pointer has not flipped — the old world must still read back
        # cleanly adoptable, and a rerun must resume and commit
        chaos.fire("grow.adopt")
        # THE adoption: one atomic rename flips every reader (workers
        # derive plan/ckpt/membership paths from the generation) to the
        # grown world
        shrink.write_world(run_dir, rec)
        # observability AFTER the commit point: the ledger append is
        # best-effort (maybe_ingest swallows every failure) and records
        # only transitions that were actually adopted
        from dgraph_tpu.obs.ledger import maybe_ingest

        maybe_ingest(
            grow_record(rec, replan_s=build_out.get("wall_s", 0.0),
                        shards=new_world),
            source="train.grow", default_on=False,
        )
        gspan.annotate(resume_step=int(resume_step))
        _logger.info(
            "grow-to-fit adopted: generation %d, world %d -> %d, joined "
            "%s, resume step %d", new_gen, W, new_world, tokens,
            resume_step,
        )
    return rec


def grant_joined(run_dir: str, rec: dict, *, attempt: int = 0) -> dict:
    """Answer the joiners a :func:`grow_world` transition adopted: write
    each token's grant (rank / generation / world size) into the OLD
    generation's membership directory — the one the joiners are polling.
    Called AFTER :func:`grow_world` returns, never inside it: the
    pointer flip is the transition's last filesystem effect, and a grant
    names a generation that must already be adopted.  Returns the
    token -> grant-record map."""
    from dgraph_tpu.comm.membership import grant_join

    if not rec.get("join_history"):
        raise GrowError("world record carries no join_history to grant")
    last = rec["join_history"][-1]
    mdir = shrink.membership_dir(run_dir, int(last["generation"]), attempt)
    return {
        token: grant_join(
            mdir, token, rank=int(rank),
            generation=int(rec["generation"]),
            world_size=int(rec["world_size"]),
        )
        for token, rank in sorted(last["joined"].items())
    }


# ---------------------------------------------------------------------------
# CLI: `python -m dgraph_tpu.train.grow --selftest true`
# ---------------------------------------------------------------------------

import dataclasses


@dataclasses.dataclass
class Config:
    """Grow-to-fit transition CLI (``--selftest`` is the compile-free
    smoke scripts/check.py gates on; the default runs one grow
    transition over ``--run_dir``'s pending joiners — the operator's
    manual scale-up trigger)."""

    selftest: bool = False
    run_dir: str = ""
    attempt: int = 0
    indent: int = 0


def _seed_world(run_dir: str, n: int = 16, world: int = 2) -> dict:
    """A tiny generation-0 elastic run with per-rank checkpoints at
    steps 0 and 3: vertex-sharded rows carry ``orig_id + 1`` so reshard
    row identity is checkable by eye, plus a replicated scalar."""
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.train.checkpoint import save_checkpoint

    edges = np.stack([np.arange(n), (np.arange(n) + 1) % n]).astype(np.int64)
    shrink.init_world(
        run_dir, edges, n, world, pad_multiple=2, lease_s=2.0,
    )
    graph = np.load(shrink.graph_path(run_dir, 0))
    counts = np.asarray(graph["counts"])
    orig = np.asarray(graph["orig_ids"])
    offsets = np.zeros(world + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    n_pad = int(ps.read_manifest(
        shrink.plan_dir(run_dir, 0))["statics"]["n_dst_pad"])
    for r in range(world):
        w = np.zeros((n_pad,), dtype=np.float64)
        own = orig[offsets[r]:offsets[r] + counts[r]]
        w[:counts[r]] = own + 1.0
        state = {"w": w, "lr": 0.5}
        for s in (0, 3):
            save_checkpoint(
                shrink.rank_ckpt_dir(run_dir, 0, r),
                {"state": state, "step": s}, s,
            )
    return {"n_pad": n_pad, "counts": counts, "orig": orig}


def _selftest() -> dict:  # noqa: C901 — one linear scenario script
    import json
    import signal
    import subprocess
    import sys
    import tempfile

    import dgraph_tpu.comm.membership as ms
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    with tempfile.TemporaryDirectory() as tmp:
        # --- fake-clock grow smoke: announce -> observe -> grow -> grant
        run_dir = os.path.join(tmp, "run")
        _seed_world(run_dir)
        clock = ms._FakeClock()
        mdir = shrink.membership_dir(run_dir, 0, 0)
        joiner = ms.Joiner(mdir, "newcomer-a", generation=0, lease_s=2.0,
                           clock=clock, sleep=clock.sleep)
        joiner.announce()
        obs = ms.Membership(mdir, rank=0, world_size=2, lease_s=2.0,
                            clock=clock, sleep=clock.sleep)
        evs = obs.poll()
        check(
            [e.token for e in evs if e.kind == "join_request"]
            == ["newcomer-a"],
            f"join not observed: {evs}",
        )
        rec = grow_world(run_dir)  # discovery from the membership dir
        check(rec["generation"] == 1 and rec["world_size"] == 3,
              f"adopted record {rec}")
        check(rec["resume_step"] == 3,
              f"resume step {rec['resume_step']} != newest common cut 3")
        check(rec["join_history"][-1]["joined"] == {"newcomer-a": 2},
              f"join history {rec['join_history']}")
        adopted = shrink.read_world(run_dir)
        check(adopted["generation"] == 1, "pointer did not flip")
        # resharded rows preserve vertex identity; replicated adopted
        g1 = np.load(shrink.graph_path(run_dir, 1))
        counts1 = np.asarray(g1["counts"])
        orig1 = np.asarray(g1["orig_ids"])
        check(int(counts1.sum()) == 16 and len(counts1) == 3,
              f"grown counts {counts1}")
        offsets1 = np.zeros(4, dtype=np.int64)
        np.cumsum(counts1, out=offsets1[1:])
        for r in range(3):
            got = restore_checkpoint(
                shrink.rank_ckpt_dir(run_dir, 1, r), step=3)
            w = np.asarray(got["state"]["w"])
            own = orig1[offsets1[r]:offsets1[r] + counts1[r]]
            check(
                np.array_equal(w[:counts1[r]], own + 1.0),
                f"rank {r} resharded rows lost vertex identity",
            )
            check(got["state"]["lr"] == 0.5, f"rank {r} replicated leaf")
        # grants land AFTER adoption, in the OLD generation's dir
        grants = grant_joined(run_dir, rec, attempt=0)
        check(grants["newcomer-a"]["rank"] == 2, f"grants {grants}")
        got = joiner.join(deadline_s=5.0)
        check(got["rank"] == 2 and got["generation"] == 1
              and got["world_size"] == 3, f"joiner grant {got}")
        # a rerun finds no pending joiners in the NEW generation
        try:
            grow_world(run_dir)
            failures.append("grow with no pending joiners did not raise")
        except GrowError as e:
            json.dumps(e.record())
        # the ledger record derives from the adopted pointer
        lrec = grow_record(rec, replan_s=0.25, shards=3)
        check(lrec["old_world"] == 2 and lrec["new_world"] == 3
              and lrec["joined"] == ["newcomer-a"],
              f"grow_record {lrec}")
        json.dumps(lrec)

    # --- subprocess sigterm pins: both crash windows leave world.json
    # pointing at a complete generation (old), and a clean rerun commits
    child = (
        "import sys; from dgraph_tpu.train import grow; "
        "grow.grow_world(sys.argv[1], tokens=['newcomer-a'])"
    )
    for name, spec in (
        ("adopt-boundary", "grow.adopt=sigterm@0"),
        ("mid-shard-stream", "plan.write=sigterm@1"),
    ):
        with tempfile.TemporaryDirectory() as tmp:
            run_dir = os.path.join(tmp, "run")
            _seed_world(run_dir)
            env = dict(os.environ)
            env["DGRAPH_CHAOS"] = spec
            env["JAX_PLATFORMS"] = "cpu"
            proc = subprocess.run(
                [sys.executable, "-c", child, run_dir],
                env=env, capture_output=True, text=True, timeout=300,
            )
            check(
                proc.returncode == -signal.SIGTERM,
                f"{name}: child exit {proc.returncode} "
                f"(stderr tail: {proc.stderr[-300:]!r})",
            )
            world = shrink.read_world(run_dir)
            check(
                world["generation"] == 0 and world["world_size"] == 2,
                f"{name}: interrupted transition left pointer at "
                f"{world['generation']} (old world must stay adopted)",
            )
            # the old generation is still fully usable AND the rerun
            # resumes the torn transition to completion
            rec = grow_world(run_dir, tokens=["newcomer-a"])
            check(
                rec["generation"] == 1 and rec["world_size"] == 3,
                f"{name}: rerun did not adopt ({rec})",
            )

    return {"kind": "grow_selftest", "failures": failures}


def main(cfg: Config) -> dict:
    import json

    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("grow.cli")
    if cfg.selftest:
        try:
            out = _selftest()
        except BaseException as e:  # every exit path carries RunHealth
            rec = {
                "kind": "grow_selftest",
                "failures": [f"crashed: {type(e).__name__}: {e}"],
                "run_health": health.finish(
                    f"grow selftest crashed: {type(e).__name__}: {e}",
                    wedge="stage_failure",
                ),
            }
            print(json.dumps(rec, indent=cfg.indent or None))
            raise
        failures = out["failures"]
        out["run_health"] = health.finish(
            "; ".join(failures) if failures else None,
            wedge="stage_failure" if failures else None,
        )
        print(json.dumps(out, indent=cfg.indent or None))
        if failures:
            raise SystemExit(
                "grow selftest FAILED: " + "; ".join(failures)
            )
        return out
    if not cfg.run_dir:
        raise SystemExit(
            "nothing to do: pass --selftest true, or --run_dir <elastic "
            "run dir> to grow it over its pending joiners"
        )
    rec = grow_world(cfg.run_dir, attempt=cfg.attempt)
    grants = grant_joined(cfg.run_dir, rec, attempt=cfg.attempt)
    out = {
        "kind": "grow_transition_cli",
        "world": rec,
        "grants": grants,
        "run_health": health.finish(),
    }
    print(json.dumps(out, indent=cfg.indent or None, default=str))
    return out


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
