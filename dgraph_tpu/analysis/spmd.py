"""Cross-rank SPMD divergence auditor: prove every rank lowers the SAME
program, in the SAME collective order.

DGraph-style full-graph training is SPMD over a vertex-partitioned graph:
every rank must trace an *identical* program or the fine-grained halo
collectives deadlock — the NCCL/NVSHMEM backends of the reference HANG,
not error, on a schedule mismatch (PAPER.md L1/L2), and XLA's collectives
are no different.  The trace tier (:mod:`~dgraph_tpu.analysis.trace`) and
the HLO tier (:mod:`~dgraph_tpu.analysis.hlo`) verify ONE rank's program
against the plan; nothing until this module verified rank-vs-rank
agreement.  And the inputs each rank builds "the same" program from are
genuinely different per rank:

- the **plan-shard subset view** (PR 8): each host loads only its own
  shard (``load_sharded_plan(ranks=[r])`` + ``assemble_plan``) — the
  statics ride the shared manifest, but a build that derived a static
  from the local rows instead would diverge silently;
- the **environment**: ``$DGRAPH_RANK``
  (:data:`~dgraph_tpu.utils.env.RANK_ENV_VAR`), ``DGRAPH_CHAOS``
  ``rank=K`` clauses, per-host tuned-record resolution
  (:func:`~dgraph_tpu.plan.resolve_halo_impl`);
- **post-shrink generations** (PR 9): after a ``train/shrink.py``
  transition every survivor re-plans from the new generation's artifact.

GSPMD-style partitioners ("Automated SPMD partitioning", PAPERS.md)
*assume* program identity across shards as ground truth and never
re-check it; this tier machine-checks the assumption, lower-only
(``jit(...).lower()`` — zero XLA compiles, jit-cache counter enforced
like the HLO tier), before the multi-host campaign can hit the
divergence/hang class at 40-GB-plan scale.

Per (program, halo lowering), each rank's step is built and lowered **as
that rank would build it** — under that rank's env, from that rank's
shard-subset plan view — then three checks run:

(a) **module identity**: all W canonicalized StableHLO modules are
    byte/hash-identical.  Canonicalization strips location metadata
    (rendered with debug info off) and forgives exactly one benign
    divergence class: a line that differs across ranks *only* by an
    integer literal equal to each rank's own id (a rank-tag constant —
    e.g. a metrics field recording the rank) is rewritten with a
    ``«RANK»`` token.  The substitution is alignment-based (same line
    count required, applied only where ranks already differ, only when
    it makes the lines EQUAL), so it can never mask a structural
    difference.  On mismatch the failure names the first divergent op
    and its producing Python frame (from the debug locations of a
    second, debug-info render).

(b) **collective issue order**: the in-program-order sequence of
    collective ops (kind, channel id, replica_groups /
    source_target_pairs, operand bytes) agrees pairwise across ranks —
    the deadlock detector proper: an order-swapped or count-mismatched
    schedule is caught even when per-rank totals match.

(c) **n_deltas symmetry**: a rank whose shard sees fewer live halo
    deltas (it sends to fewer peers — exactly the PR 8 subset-view /
    PR 9 shrink hazard) would emit fewer ppermute rounds IF the program
    consulted the local view.  The auditor computes each rank's locally
    observable live-delta set and proves the asymmetry either absent
    (all sets equal) or program-invariant (sets differ but every rank's
    module is still identical — the program provably uses the manifest's
    global ``halo_deltas``).

Plus a **tuned-resolution agreement** check: each rank resolves its halo
lowering through :func:`~dgraph_tpu.plan.resolve_halo_impl` under its own
(simulated) adopted record; divergent resolution is reported before any
lowering — a rank-divergent tune record is a deadlock at step one.

The zero-filled completion of a rank's plan view is sound for lowering:
a rank never holds its peers' rows, lowering consumes only shapes +
statics, and a program whose *structure* depended on peer row values
would not be SPMD in the first place — that dependence is exactly what
the cross-rank comparison would surface.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import re
import tempfile
from typing import Callable, Dict, List, Optional

from dgraph_tpu.analysis.hlo import (
    COLLECTIVE_HLO_OPS,
    _dense_2d,
    _elt_info,
    _jit_cache_entries,
    lower_program,
)
from dgraph_tpu.analysis.trace import HALO_IMPLS, AuditWorkload, PROGRAMS
from dgraph_tpu.utils.env import RANK_ENV_VAR

__all__ = [
    "build_spmd_fixture",
    "build_shrink_fixture",
    "build_rank_workload",
    "rank_live_deltas",
    "canonical_module_text",
    "canonicalize_rank_modules",
    "collective_sequence",
    "resolution_agreement",
    "audit_plan_dir_spmd",
    "spmd_drift_record",
    "spmd_selftest",
]

RANK_TOKEN = "«RANK»"

# statics a rank's plan view must agree on with every peer: one drifted
# value here changes traced round counts / operand shapes program-wide
_STATIC_FIELDS = (
    "world_size", "n_src_pad", "n_dst_pad", "e_pad", "halo_side",
    "homogeneous", "owner_sorted", "halo_deltas", "scatter_mc",
    "scatter_block_e", "scatter_block_n", "halo_sort_mc", "gather_mv",
    # the FULL-WORLD traffic matrix and the schedule compiled from it
    # (dgraph_tpu.sched): a rank whose matrix row drifted compiles a
    # different round order — the deadlock class the sched lowering adds
    "halo_pair_rows", "halo_schedule",
    # the wire format attached at build time (dgraph_tpu.wire): a rank
    # whose format drifted encodes collective operands at a different
    # dtype/width — every exchange rendezvous disagrees on byte counts
    "wire_format",
)


@contextlib.contextmanager
def _rank_env(rank: int):
    """Simulate one rank's process env (``$DGRAPH_RANK``) for the
    duration of a build+lower — restored unconditionally."""
    old = os.environ.get(RANK_ENV_VAR)
    os.environ[RANK_ENV_VAR] = str(int(rank))
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(RANK_ENV_VAR, None)
        else:
            os.environ[RANK_ENV_VAR] = old


# ---------------------------------------------------------------------------
# fixtures: sharded plan artifacts (and a shrink run) for the audit
# ---------------------------------------------------------------------------


def _fixture_graph(world_size: int, num_nodes: int, num_edges: int,
                   seed: int):
    """The canonical audit graph (same construction as
    :func:`~dgraph_tpu.analysis.trace.build_audit_workload`, so the spmd
    tier audits the same workload shape the other tiers pin)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    part = np.sort(rng.integers(0, world_size, num_nodes)).astype(np.int32)
    edges = np.stack([
        rng.integers(0, num_nodes, num_edges),
        rng.integers(0, num_nodes, num_edges),
    ])
    return edges, part


def build_spmd_fixture(
    world_size: int,
    out_dir: str,
    *,
    num_nodes: int = 48,
    num_edges: int = 300,
    seed: int = 0,
) -> str:
    """Write the v8 sharded-plan artifact the cross-rank audit loads its
    per-rank views from (``overlap=True`` so all four halo lowerings are
    legal; no O(E) layout sidecar — per-rank loading never reads it)."""
    from dgraph_tpu.plan import build_plan_shards

    edges, part = _fixture_graph(world_size, num_nodes, num_edges, seed)
    build_plan_shards(
        edges, part, out_dir=out_dir, world_size=world_size, overlap=True,
        write_layout=False,
    )
    return out_dir


def build_shrink_fixture(
    run_dir: str,
    *,
    world_size: int = 3,
    num_nodes: int = 48,
    num_edges: int = 240,
    seed: int = 0,
) -> dict:
    """A real ``train/shrink.py`` W -> W-1 transition: init generation 0,
    make one checkpoint step durable on every rank (the consistent cut
    ``shrink_world`` requires), lose the last rank.  Returns the adopted
    world record; ``plan_dir(run_dir, g)`` for g in {0, 1} are the two
    generations the cross-rank audit then verifies."""
    import numpy as np

    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.train import shrink
    from dgraph_tpu.train.checkpoint import save_checkpoint

    edges, _ = _fixture_graph(world_size, num_nodes, num_edges, seed)
    shrink.init_world(
        run_dir, edges, num_nodes, world_size, seed=seed, overlap=True,
    )
    statics = ps.read_manifest(shrink.plan_dir(run_dir, 0))["statics"]
    n_pad = int(statics["n_dst_pad"])
    for r in range(world_size):
        save_checkpoint(
            shrink.rank_ckpt_dir(run_dir, 0, r),
            {"state": {"w": np.zeros((n_pad, 2), np.float32)}, "step": 0},
            0,
        )
    return shrink.shrink_world(run_dir, [world_size - 1])


# ---------------------------------------------------------------------------
# per-rank plan views and workloads
# ---------------------------------------------------------------------------


def _expand_rank_view(sub_plan, rank: int, world_size: int):
    """Zero-filled full-``[W]`` completion of one rank's subset plan view
    (leading axis 1 -> W, the rank's own row in its slot).  Shapes and
    statics are exactly what the rank knows; peer rows — which the rank
    never holds — are zeros, which lowering (shapes only) cannot see."""
    import numpy as np
    import jax

    def expand(leaf):
        arr = np.asarray(leaf)
        out = np.zeros((world_size,) + arr.shape[1:], arr.dtype)
        out[rank] = arr[0]
        return out

    return jax.tree.map(expand, sub_plan)


def rank_live_deltas(sub_plan, rank: int) -> tuple:
    """The live halo deltas OBSERVABLE from one rank's own shard: deltas
    ``(p - rank) % W`` for peers p this rank sends at least one real halo
    row to.  (Receive liveness lives in the peers' shards — exactly why a
    per-rank derivation of ``halo_deltas`` would be asymmetric.)"""
    import numpy as np

    W = int(sub_plan.world_size)
    mask = np.asarray(sub_plan.halo.send_mask)[0]  # [W, S]
    live = set()
    for p in range(W):
        if p != rank and mask[p].any():
            live.add((p - rank) % W)
    return tuple(sorted(live))


def _plan_statics(plan) -> dict:
    out = {k: getattr(plan, k) for k in _STATIC_FIELDS}
    out["s_pad"] = int(plan.halo.s_pad)
    out["halo_deltas"] = tuple(int(d) for d in plan.halo_deltas)
    out["overlap"] = plan.overlap is not None
    if plan.overlap is not None:
        out["e_int_pad"] = int(plan.overlap.e_int_pad)
        out["e_bnd_pad"] = int(plan.overlap.e_bnd_pad)
    return out


def build_rank_workload(
    plan_dir: str,
    rank: int,
    **workload_kwargs,
) -> AuditWorkload:
    """Build the audit workload **as rank ``rank`` would build it**: the
    plan comes from that rank's shard-subset view
    (``load_sharded_plan(ranks=[rank])`` -> :func:`~dgraph_tpu.plan.
    assemble_plan`), everything downstream (batch shapes, model init,
    optimizer state) is derived from that view's statics through the
    SAME scaffolding the other tiers audit
    (:func:`~dgraph_tpu.analysis.trace.workload_from_plan` — structural
    sameness, not parallel-edit sameness), and the whole build runs
    under that rank's env (``$DGRAPH_RANK``).  Abstract throughout:
    params/opt_state are ``eval_shape`` trees, the batch is zeros —
    nothing compiles, nothing touches a device buffer."""
    from dgraph_tpu.analysis.trace import workload_from_plan
    from dgraph_tpu.plan import load_sharded_plan

    with _rank_env(rank):
        sub, _ = load_sharded_plan(
            plan_dir, ranks=[rank], load_layout=False
        )
        plan = _expand_rank_view(sub, rank, int(sub.world_size))
        return workload_from_plan(plan, **workload_kwargs)


# ---------------------------------------------------------------------------
# canonicalization + ordered collective walk
# ---------------------------------------------------------------------------


def canonical_module_text(lowered) -> str:
    """The lowered StableHLO module rendered WITHOUT debug info (no
    ``loc(...)`` / ``#loc`` metadata — the only per-build noise in the
    asm) — the byte string the cross-rank identity check hashes."""
    module = lowered.compiler_ir(dialect="stablehlo")
    return module.operation.get_asm(enable_debug_info=False)


def _rank_id_sub(line: str, rank: int) -> str:
    """Rewrite standalone occurrences of ``rank``'s own integer id to the
    RANK token (word/float boundaries guarded: ``dense<1>`` rewrites,
    ``tensor<1x8xf32>``'s dim and ``1.000000e+00`` do not)."""
    return re.sub(
        rf"(?<![\w.]){rank}(?![\w.])", RANK_TOKEN, line
    )


def canonicalize_rank_modules(texts: Dict[int, str]) -> tuple:
    """Alignment-based benign-divergence canonicalization over per-rank
    module texts.  Returns ``(canonical: dict, rank_tag_lines: int)``.

    Only lines where ranks ALREADY differ are touched, and a line is
    rewritten only when substituting each rank's own id makes all ranks'
    lines EQUAL — a pure rank-tag constant.  Anything else (different op,
    different shape, different order, different count) survives verbatim
    and fails the identity check.  Modules with different line counts are
    returned unchanged: that is structural divergence by definition."""
    ranks = sorted(texts)
    lines = {r: texts[r].splitlines() for r in ranks}
    if len({len(v) for v in lines.values()}) != 1:
        return dict(texts), 0
    n = len(lines[ranks[0]])
    subs = 0
    for i in range(n):
        row = {r: lines[r][i] for r in ranks}
        if len(set(row.values())) == 1:
            continue
        cand = {r: _rank_id_sub(row[r], r) for r in ranks}
        if len(set(cand.values())) == 1 and cand[ranks[0]] != row[ranks[0]]:
            for r in ranks:
                lines[r][i] = cand[r]
            subs += 1
    return {r: "\n".join(lines[r]) for r in ranks}, subs


def _walk_ops(module):
    """Every op of a StableHLO module in PROGRAM ORDER (pre-order over
    regions/blocks) — the order XLA will issue collectives in."""

    def rec(op):
        yield op
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    yield from rec(child.operation)

    yield from rec(module.operation)


def _tensor_info(t):
    import math

    from jaxlib.mlir import ir

    rt = ir.RankedTensorType(t)
    shape = tuple(int(s) for s in rt.shape)
    np_dtype, nbytes = _elt_info(str(rt.element_type))
    return shape, np_dtype, int(math.prod(shape)) * nbytes


def collective_sequence(lowered) -> List[dict]:
    """The module's collective ISSUE sequence, in program order: op kind,
    channel id, replica_groups / source_target_pairs, operand bytes.
    Two ranks whose sequences differ anywhere — order, kind, peers,
    payload — are a deadlock on real transports (each side waits for the
    other's next collective, which never comes)."""
    module = lowered.compiler_ir(dialect="stablehlo")
    seq = []
    for op in _walk_ops(module):
        name = op.name
        if not name.startswith("stablehlo."):
            continue
        kind = name[len("stablehlo."):]
        if kind not in COLLECTIVE_HLO_OPS or not op.operands:
            continue
        attrs = {a.name: a.attr for a in op.attributes}
        shape, np_dtype, nbytes = _tensor_info(op.operands[0].type)
        channel = attrs.get("channel_handle")
        m = re.search(r"handle\s*=\s*(\d+)", str(channel)) if channel else None
        seq.append({
            "op": kind,
            "shape": list(shape),
            "dtype": np_dtype,
            "bytes": nbytes,
            "channel_id": int(m.group(1)) if m else None,
            "replica_groups": _dense_2d(attrs.get("replica_groups")),
            "source_target_pairs": _dense_2d(
                attrs.get("source_target_pairs")
            ),
        })
    return seq


def _short_loc(loc: str) -> str:
    """Condense an MLIR callsite chain to ``scope @ file:line`` (the
    producing Python frame) — the full chain is pages long."""
    scope = re.match(r'loc\("([^"]+)"', loc)
    frame = re.search(r'"([^"<][^"]*)":(\d+):\d+', loc)
    out = scope.group(1) if scope else ""
    if frame:
        out += f" @ {frame.group(1)}:{frame.group(2)}"
    return out or loc[:160]


def _op_fingerprints(lowered) -> List[tuple]:
    """(op name, result types, attributes) per op in program order, plus
    the op's debug location — the divergence-naming walk (locations come
    from THIS render; the identity check's render has them stripped)."""
    module = lowered.compiler_ir(dialect="stablehlo")
    out = []
    for op in _walk_ops(module):
        attrs = tuple(sorted(
            (a.name, str(a.attr)) for a in op.attributes
        ))
        results = tuple(str(r.type) for r in op.results)
        out.append((op.name, results, attrs, _short_loc(str(op.location))))
    return out


def _first_divergent_op(fp_a: list, fp_b: list, rank_a: int, rank_b: int):
    """First program-order op whose (name, results, attrs) fingerprint
    differs between two ranks' modules, with both producing frames."""
    for i, (a, b) in enumerate(zip(fp_a, fp_b)):
        if a[:3] != b[:3]:
            return (
                f"op #{i}: rank {rank_a} lowered {a[0]!r} "
                f"(from {a[3]}), rank {rank_b} lowered {b[0]!r} "
                f"(from {b[3]})"
            )
    if len(fp_a) != len(fp_b):
        i = min(len(fp_a), len(fp_b))
        longer, who = (fp_a, rank_a) if len(fp_a) > len(fp_b) else (fp_b, rank_b)
        return (
            f"op #{i}: rank {who} lowered {len(longer) - i} extra op(s), "
            f"first {longer[i][0]!r} (from {longer[i][3]})"
        )
    return "modules differ only in attribute/metadata text"


def _issue_key(entry: dict) -> tuple:
    """A collective's order-independent identity: everything except the
    channel id, which XLA assigns in ISSUE order — two ranks that swap
    two collectives also swap the channel numbering, so the swap must be
    recognized on the op's own parameters."""
    return tuple(
        (k, repr(v)) for k, v in sorted(entry.items()) if k != "channel_id"
    )


def _compare_sequences(seq0: list, seq_r: list, rank: int, label: str,
                       failures: list) -> None:
    """Pairwise collective-schedule agreement (rank 0 vs rank ``rank``):
    the deadlock detector proper."""
    if len(seq0) != len(seq_r):
        failures.append(
            f"[spmd:{label}] collective COUNT mismatch: rank 0 issues "
            f"{len(seq0)} collectives, rank {rank} issues {len(seq_r)} — "
            f"on a real transport the long side blocks forever on round "
            f"{min(len(seq0), len(seq_r))}"
        )
        return
    for i, (a, b) in enumerate(zip(seq0, seq_r)):
        if a == b:
            continue
        # a swap: the collective rank `rank` issues HERE, rank 0 issues
        # LATER (or vice versa) — same multiset, different order
        later = any(
            _issue_key(b) == _issue_key(seq0[j])
            for j in range(i + 1, len(seq0))
        ) or any(
            _issue_key(a) == _issue_key(seq_r[j])
            for j in range(i + 1, len(seq_r))
        )
        what = (
            "ORDER-swapped collective schedule"
            if later else "collective-parameter drift"
        )
        failures.append(
            f"[spmd:{label}] {what} at issue #{i}: rank 0 issues "
            f"{a['op']}(channel={a['channel_id']}, bytes={a['bytes']}, "
            f"pairs={a['source_target_pairs']}), rank {rank} issues "
            f"{b['op']}(channel={b['channel_id']}, bytes={b['bytes']}, "
            f"pairs={b['source_target_pairs']}) — mismatched peers "
            f"rendezvous on different collectives and deadlock"
        )
        return


# ---------------------------------------------------------------------------
# tuned-record resolution agreement
# ---------------------------------------------------------------------------


def resolution_agreement(
    world_size: int,
    halo_deltas: tuple,
    *,
    overlap_available: bool,
    sched_available: bool = False,
    pair_rows: tuple = (),
    rank_tuned: Optional[Dict[int, Optional[str]]] = None,
    plan_wire_format: str = "fp32",
    rank_tuned_wire: Optional[Dict[int, Optional[str]]] = None,
    failures: Optional[list] = None,
) -> dict:
    """Resolve the halo lowering AND the wire format PER RANK through
    the real :func:`~dgraph_tpu.plan.resolve_halo_impl` /
    :func:`~dgraph_tpu.wire.spec.resolve_wire_format` ladders, each rank
    under its own (simulated) adopted tuning record — divergent
    resolution means the ranks would not even agree on the transport
    family (or would encode collective operands at different widths), a
    deadlock before the first exchange.  Appends to ``failures`` and
    returns ``{rank: [impl, source, wire_format, wire_source]}``."""
    from dgraph_tpu import config as _cfg
    from dgraph_tpu.plan import resolve_halo_impl
    from dgraph_tpu.wire.spec import resolve_wire_format

    rank_tuned = rank_tuned or {}
    rank_tuned_wire = rank_tuned_wire or {}
    out = {}
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl,
             _cfg.wire_format, _cfg.tuned_wire_format)
    try:
        for r in range(world_size):
            with _rank_env(r):
                _cfg.set_flags(
                    halo_impl="auto", tuned_halo_impl=rank_tuned.get(r),
                    wire_format="auto",
                    tuned_wire_format=rank_tuned_wire.get(r),
                )
                impl, source = resolve_halo_impl(
                    world_size, tuple(halo_deltas),
                    overlap_available=overlap_available,
                    p2p_available=True,
                    sched_available=sched_available,
                    pair_rows=pair_rows,
                )
                wf, wf_source = resolve_wire_format(
                    world_size, tuple(halo_deltas),
                    plan_format=plan_wire_format,
                )
                out[r] = [impl, source, wf, wf_source]
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            wire_format=saved[2], tuned_wire_format=saved[3],
        )
    if failures is not None:
        if len({(v[0], v[1]) for v in out.values()}) > 1:
            failures.append(
                f"[spmd:resolution] ranks resolve DIFFERENT halo "
                f"lowerings: {out} — a rank-divergent tuned record (or "
                f"env pin) splits the transport family before the first "
                f"exchange"
            )
        if len({(v[2], v[3]) for v in out.values()}) > 1:
            failures.append(
                f"[spmd:resolution] ranks resolve DIFFERENT wire "
                f"formats: {out} — a rank-divergent tuned record (or "
                f"env pin) makes peers encode/decode collective operands "
                f"at different widths; every rendezvous disagrees on "
                f"byte counts"
            )
    return out


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------


def _call_builder(build: Callable, w: AuditWorkload, rank: int):
    """Program builders are rank-agnostic by default
    (:data:`~dgraph_tpu.analysis.trace.PROGRAMS`); mutant builders (the
    selftest's seeded divergences) take ``(w, rank)``."""
    import inspect

    params = inspect.signature(build).parameters
    if len(params) >= 2:
        return build(w, rank)
    return build(w)


def audit_plan_dir_spmd(
    plan_dir: str,
    *,
    impls=HALO_IMPLS,
    programs: Optional[dict] = None,
    rank_tuned: Optional[Dict[int, Optional[str]]] = None,
    rank_tuned_wire: Optional[Dict[int, Optional[str]]] = None,
    label: str = "",
    workload_kwargs: Optional[dict] = None,
) -> dict:
    """Run the full cross-rank audit over one sharded-plan artifact:
    build + lower every (program, halo lowering) pair per rank — each
    rank from its own shard-subset view, under its own env — and verify
    module identity (a), collective issue order (b), n_deltas symmetry
    (c), and tuned-resolution agreement.  Lower-only: the jit cache of
    every built program must stay empty (counter in the report, failure
    otherwise).  Returns a ``kind="spmd_audit"`` report dict (``ok`` +
    ``failures``; the caller decides whether to raise)."""
    from dgraph_tpu import config as _cfg
    from dgraph_tpu import plan_shards as ps
    from dgraph_tpu.plan import load_sharded_plan

    manifest = ps.read_manifest(plan_dir)
    W = int(manifest["world_size"])
    prefix = f"{label}/" if label else ""
    failures: list = []

    # per-rank plan views: statics agreement + locally observable deltas
    statics_by_rank, live_by_rank = {}, {}
    for r in range(W):
        with _rank_env(r):
            sub, _ = load_sharded_plan(plan_dir, ranks=[r], load_layout=False)
        statics_by_rank[r] = _plan_statics(sub)
        live_by_rank[r] = rank_live_deltas(sub, r)
    base = statics_by_rank[0]
    for r in range(1, W):
        if statics_by_rank[r] != base:
            diff = {
                k: (base[k], statics_by_rank[r][k])
                for k in base
                if statics_by_rank[r].get(k) != base[k]
            }
            failures.append(
                f"[spmd:{prefix}statics] rank {r}'s plan view disagrees "
                f"with rank 0 on {diff} — every traced shape/round count "
                f"downstream diverges"
            )
    halo_deltas = base["halo_deltas"]

    # tuned-record resolution agreement (each rank under its own record)
    resolution = resolution_agreement(
        W, halo_deltas, overlap_available=base.get("overlap", False),
        sched_available=base.get("halo_schedule") is not None,
        pair_rows=base.get("halo_pair_rows", ()),
        rank_tuned=rank_tuned,
        plan_wire_format=base.get("wire_format", "fp32"),
        rank_tuned_wire=rank_tuned_wire, failures=failures,
    )

    # per-rank workloads, built under each rank's env (skipped when the
    # caller asked for the static checks only, impls=())
    wk = dict(workload_kwargs or {})
    workloads = (
        {r: build_rank_workload(plan_dir, r, **wk) for r in range(W)}
        if impls else {}
    )

    program_records: list = []
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl, _cfg.use_pallas_p2p)
    schedule_ok = True
    audited_impls = [
        i for i in impls
        if i != "sched" or base.get("halo_schedule") is not None
    ]
    try:
        for impl in audited_impls:
            _cfg.set_flags(halo_impl=impl, tuned_halo_impl=None)
            _cfg.set_flags(
                use_pallas_p2p=True if impl == "pallas_p2p" else saved[2]
            )
            for plabel, build in (programs or PROGRAMS).items():
                tag = f"{prefix}{plabel}/{impl}"
                texts, seqs, lowereds, cache = {}, {}, {}, {}
                for r in range(W):
                    with _rank_env(r):
                        fn, args = _call_builder(build, workloads[r], r)
                        lowered = lower_program(fn, args)
                        texts[r] = canonical_module_text(lowered)
                        seqs[r] = collective_sequence(lowered)
                        lowereds[r] = lowered
                        cache[r] = _jit_cache_entries(fn)
                    if cache[r] is None:
                        failures.append(
                            f"[spmd:{tag}] rank {r}: jit-cache probe "
                            f"unavailable — the lower-only contract is "
                            f"unenforceable; update analysis for this jax "
                            f"version"
                        )
                    elif cache[r]:
                        failures.append(
                            f"[spmd:{tag}] rank {r}: jit cache holds "
                            f"{cache[r]} executable(s) after a lower-only "
                            f"audit — something compiled"
                        )

                canon, rank_tags = canonicalize_rank_modules(texts)
                hashes = {
                    r: hashlib.sha256(canon[r].encode()).hexdigest()[:16]
                    for r in canon
                }
                identical = len(set(hashes.values())) == 1
                if not identical:
                    fp0 = _op_fingerprints(lowereds[0])
                    for r in range(1, W):
                        if hashes[r] == hashes[0]:
                            continue
                        failures.append(
                            f"[spmd:{tag}] rank {r}'s canonicalized "
                            f"StableHLO differs from rank 0's "
                            f"({hashes[0]} vs {hashes[r]}); first "
                            f"divergence — "
                            + _first_divergent_op(
                                fp0, _op_fingerprints(lowereds[r]), 0, r
                            )
                        )
                        break  # one named divergence per pair is enough
                n_sched = len(failures)
                for r in range(1, W):
                    _compare_sequences(seqs[0], seqs[r], r, tag, failures)
                if len(failures) > n_sched or not identical:
                    schedule_ok = False
                program_records.append({
                    "program": plabel,
                    "impl": impl,
                    "module_hash": hashes,
                    "identical": identical,
                    "rank_tag_lines": rank_tags,
                    "num_collectives": len(seqs[0]),
                    "jit_cache_entries": cache,
                })
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            use_pallas_p2p=saved[2],
        )

    # (c) n_deltas symmetry: absent, or proven program-invariant by the
    # very identity the modules just demonstrated. In static-only mode
    # (impls=() — nothing lowered) an asymmetric view is REPORTED but not
    # failed: there is no program evidence either way.
    sym = "symmetric"
    if len({live_by_rank[r] for r in live_by_rank}) > 1:
        if not program_records:
            sym = "asymmetric_not_lowered"
        elif schedule_ok:
            sym = "asymmetric_program_invariant"
        else:
            sym = "asymmetric"
            failures.append(
                f"[spmd:{prefix}n_deltas] per-rank live-delta views differ "
                f"({ {r: list(v) for r, v in live_by_rank.items()} }) AND "
                f"the lowered programs diverge — a rank that sees fewer "
                f"live deltas is emitting a different round schedule (the "
                f"rank-subset / shrink hazard)"
            )

    return {
        "kind": "spmd_audit",
        "plan_dir": plan_dir,
        "label": label,
        "world_size": W,
        "num_halo_deltas": len(halo_deltas),
        "halo_deltas": list(halo_deltas),
        "impls": list(audited_impls),
        "programs": program_records,
        "statics_agree": not any("statics" in f for f in failures),
        "per_rank_live_deltas": {
            str(r): list(v) for r, v in live_by_rank.items()
        },
        "delta_symmetry": sym,
        "resolution": {str(r): v for r, v in resolution.items()},
        "failures": failures,
        "ok": not failures,
    }


# ---------------------------------------------------------------------------
# bench fallback record
# ---------------------------------------------------------------------------


def spmd_drift_record(
    world_size: int = 4, *, num_nodes: int = 1024, num_edges: int = 4096,
    feat_dim: int = 16, seed: int = 0,
) -> dict:
    """Compact cross-rank identity record for bench's no-healthy-chip
    fallback (ROADMAP item 5, FOURTH non-null tier beside
    ``schedule_drift``, ``cpu_scan_delta``, and ``hlo_drift``): the
    TRAIN step only, one row per halo lowering with the per-rank module
    hashes and the schedule-identity verdict — a wedged round still
    lands a non-null signal about whether the ranks would have agreed
    on a collective schedule at all."""
    from dgraph_tpu.analysis.trace import _train_program

    with tempfile.TemporaryDirectory(prefix="dgraph_spmd_drift_") as tmp:
        build_spmd_fixture(
            world_size, tmp, num_nodes=num_nodes, num_edges=num_edges,
            seed=seed,
        )
        report = audit_plan_dir_spmd(
            tmp, programs={"train_step": _train_program},
            workload_kwargs={"feat_dim": feat_dim},
        )
    per_impl = {
        rec["impl"]: {
            "identical": rec["identical"],
            "num_collectives": rec["num_collectives"],
            "rank_tag_lines": rec["rank_tag_lines"],
        }
        for rec in report["programs"]
    }
    return {
        "kind": "spmd_drift",
        "workload": {
            "world_size": world_size, "nodes": num_nodes,
            "edges": num_edges, "feat_dim": feat_dim, "seed": seed,
        },
        "num_halo_deltas": report["num_halo_deltas"],
        "delta_symmetry": report["delta_symmetry"],
        "train_step_by_impl": per_impl,
        "failures": report["failures"],
        "drift": not report["ok"],
    }


# ---------------------------------------------------------------------------
# seeded divergence mutants (the selftest's vacuity guards)
# ---------------------------------------------------------------------------


def mutant_dropped_round_program(w: AuditWorkload, rank: int):
    """Rank 1 drops the last live delta from its round schedule — the
    PR 8/9 hazard in its purest form.  Every other rank spins on the
    missing round's ``collective_permute`` forever on real transports;
    here it MUST turn both the module-identity and the issue-sequence
    checks red."""
    import jax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import collectives
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    deltas = tuple(w.plan_np.halo_deltas)
    my_deltas = deltas[:-1] if rank == 1 else deltas

    def stepish(xs, plan):
        def body(plan_, x):
            p = squeeze_plan(plan_)
            buf = collectives.halo_exchange(
                x[0], p.halo, GRAPH_AXIS, deltas=my_deltas, impl="ppermute",
            )
            return buf.sum()[None]

        return jax.shard_map(
            body, mesh=w.mesh,
            in_specs=(plan_in_specs(w.plan), P(GRAPH_AXIS)),
            out_specs=P(GRAPH_AXIS),
            **collectives.shard_map_checks(impl="ppermute"),
        )(plan, xs)

    return jax.jit(stepish), (w.batch["x"], w.plan)


def mutant_swapped_order_program(w: AuditWorkload, rank: int):
    """Two collectives, issued in RANK-DEPENDENT order (rank 1 swaps
    them) — per-rank totals match exactly, so only the issue-sequence
    comparison can catch it.  Needs >= 2 live deltas."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    W = w.world_size
    deltas = tuple(w.plan_np.halo_deltas)
    if len(deltas) < 2:
        raise ValueError(
            f"the swapped-order mutant needs >= 2 live deltas (have "
            f"{deltas}); use a wider fixture"
        )
    order = deltas[:2] if rank != 1 else deltas[:2][::-1]

    def stepish(xs):
        def body(x):
            out = x[0]
            for d in order:
                perm = [(i, (i + d) % W) for i in range(W)]
                out = out + lax.ppermute(out, GRAPH_AXIS, perm)
            return out[None]

        return jax.shard_map(
            body, mesh=w.mesh, in_specs=(P(GRAPH_AXIS),),
            out_specs=P(GRAPH_AXIS),
            **shard_map_checks(relax="seeded spmd vacuity mutant"),
        )(xs)

    return jax.jit(stepish), (w.batch["x"],)


def benign_rank_tag_program(w: AuditWorkload, rank: int):
    """A rank-id CONSTANT folded into the module (a metrics tag — the
    one benign per-rank difference) alongside a normal collective: the
    canonicalizer must substitute it and the audit must stay GREEN."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    W = w.world_size

    def stepish(xs):
        def body(x):
            out = x[0] + lax.ppermute(
                x[0], GRAPH_AXIS, [(i, (i + 1) % W) for i in range(W)]
            )
            return out[None], jnp.int32(rank)

        return jax.shard_map(
            body, mesh=w.mesh, in_specs=(P(GRAPH_AXIS),),
            out_specs=(P(GRAPH_AXIS), P()),
            **shard_map_checks(relax="rank tag replicated by construction"),
        )(xs)

    return jax.jit(stepish), (w.batch["x"],)


# ---------------------------------------------------------------------------
# selftest (the vacuity guards; __main__'s --selftest and the standalone
# CLI both run this)
# ---------------------------------------------------------------------------


def _check(failures: list, cond, msg: str) -> None:
    if not cond:
        failures.append(msg)


def spmd_selftest(log=None, *, seed: int = 0) -> dict:
    """The cross-rank audit's tier-1 registration: clean 2- AND 4-shard
    worlds across all four halo lowerings, one real shrink (W -> W-1)
    transition's both generations, and the seeded-divergence vacuity
    mutants (dropped round on rank 1, swapped two-collective order,
    rank-divergent tune record) that must each go RED — plus the benign
    rank-tag constant that must stay GREEN.  Zero XLA compiles
    throughout; every program's jit-cache counter rides the report."""
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.train import shrink as shr

    failures: list = []
    summary: dict = {"kind": "spmd_selftest"}
    with tempfile.TemporaryDirectory(prefix="dgraph_spmd_selftest_") as tmp:
        # clean cross-rank audits: every program, every lowering, W=2 and 4
        w4_dir = None
        for W in (2, 4):
            d = build_spmd_fixture(W, os.path.join(tmp, f"w{W}"), seed=seed)
            rep = audit_plan_dir_spmd(d, label=f"w{W}")
            if log is not None:
                log.write(rep)
            _check(
                failures, rep["ok"],
                f"{W}-shard cross-rank audit drifted: {rep['failures']}",
            )
            _check(
                failures, rep["num_halo_deltas"] >= 1,
                f"{W}-shard spmd fixture has no cross-rank traffic "
                f"(the identity checks would be vacuous)",
            )
            summary[f"w{W}"] = {
                "ok": rep["ok"],
                "delta_symmetry": rep["delta_symmetry"],
                "num_halo_deltas": rep["num_halo_deltas"],
                "programs_identical": all(
                    p["identical"] for p in rep["programs"]
                ),
                "jit_cache_entries": max(
                    (c or 0)
                    for p in rep["programs"]
                    for c in p["jit_cache_entries"].values()
                ),
            }
            if W == 4:
                w4_dir = d

        # one REAL shrink transition: audit both generations (train step,
        # all four lowerings) — the post-shrink world must re-agree
        rund = os.path.join(tmp, "shrink")
        world = build_shrink_fixture(rund, world_size=3, seed=seed)
        _check(
            failures, world["world_size"] == 2 and world["generation"] == 1,
            f"shrink fixture did not adopt a W-1 world: {world}",
        )
        for gen, wsz in ((0, 3), (1, 2)):
            rep = audit_plan_dir_spmd(
                shr.plan_dir(rund, gen),
                programs={"train_step": _train_program},
                label=f"shrink_g{gen}",
            )
            if log is not None:
                log.write(rep)
            _check(
                failures, rep["world_size"] == wsz,
                f"shrink generation {gen} plan is for world "
                f"{rep['world_size']}, expected {wsz}",
            )
            _check(
                failures, rep["ok"],
                f"post-shrink generation {gen} cross-rank audit drifted: "
                f"{rep['failures']}",
            )
            summary[f"shrink_g{gen}"] = {
                "ok": rep["ok"], "world_size": rep["world_size"],
                "delta_symmetry": rep["delta_symmetry"],
            }

        # vacuity mutants on the 4-shard fixture (>= 2 live deltas there)
        mutants = {}

        rep = audit_plan_dir_spmd(
            w4_dir, impls=("ppermute",),
            programs={"mutant_drop": mutant_dropped_round_program},
            label="mutant_drop",
        )
        mutants["dropped_round"] = not rep["ok"]
        _check(
            failures, not rep["ok"],
            "auditor accepted a rank-dependent branch that DROPS a "
            "ppermute round on rank 1",
        )
        _check(
            failures,
            any("COUNT mismatch" in f or "differs" in f
                for f in rep["failures"]),
            f"dropped-round divergence was red for the wrong reason: "
            f"{rep['failures'][:2]}",
        )

        rep = audit_plan_dir_spmd(
            w4_dir, impls=("ppermute",),
            programs={"mutant_swap": mutant_swapped_order_program},
            label="mutant_swap",
        )
        mutants["swapped_order"] = not rep["ok"]
        _check(
            failures, not rep["ok"],
            "auditor accepted a rank-dependent SWAP of two collectives "
            "(equal per-rank totals — the pure ordering deadlock)",
        )
        _check(
            failures,
            any("ORDER" in f for f in rep["failures"]),
            f"swapped-order divergence missed by the issue-sequence "
            f"comparator: {rep['failures'][:2]}",
        )

        # a rank-divergent adopted tuning record must fail resolution
        # agreement before anything lowers
        rep = audit_plan_dir_spmd(
            w4_dir, impls=(), programs={},
            rank_tuned={0: "all_to_all", 1: "ppermute"},
            label="mutant_tuned",
        )
        mutants["divergent_tune_record"] = not rep["ok"]
        _check(
            failures, not rep["ok"],
            "auditor accepted rank-divergent tuned-record resolution",
        )
        _check(
            failures,
            any("resolution" in f for f in rep["failures"]),
            f"divergent tune record was red for the wrong reason: "
            f"{rep['failures'][:2]}",
        )

        # a rank-divergent adopted WIRE-FORMAT record must likewise fail
        # resolution agreement before anything lowers (rank 1 encodes
        # bf16 while rank 0 sends fp32 — byte counts disagree at every
        # rendezvous)
        rep = audit_plan_dir_spmd(
            w4_dir, impls=(), programs={},
            rank_tuned_wire={0: None, 1: "bf16"},
            label="mutant_wire",
        )
        mutants["divergent_wire_record"] = not rep["ok"]
        _check(
            failures, not rep["ok"],
            "auditor accepted rank-divergent wire-format resolution",
        )
        _check(
            failures,
            any("wire" in f for f in rep["failures"]),
            f"divergent wire record was red for the wrong reason: "
            f"{rep['failures'][:2]}",
        )

        # the benign rank-tag constant must stay GREEN (canonicalized),
        # proving the identity check doesn't cry wolf on rank identity
        rep = audit_plan_dir_spmd(
            w4_dir, impls=("ppermute",),
            programs={"benign_tag": benign_rank_tag_program},
            label="benign_tag",
        )
        mutants["benign_rank_tag_green"] = rep["ok"]
        _check(
            failures, rep["ok"],
            f"canonicalization failed to forgive a benign rank-id "
            f"constant: {rep['failures'][:2]}",
        )
        _check(
            failures,
            any(p["rank_tag_lines"] > 0 for p in rep["programs"]),
            "benign rank-tag program embedded no rank constant — the "
            "canonicalization check is vacuous",
        )

        summary["mutants"] = mutants
    summary["failures"] = failures
    summary["ok"] = not failures
    return summary


# ---------------------------------------------------------------------------
# CLI (scripts/check.py runs this standalone; the package CLI embeds it)
# ---------------------------------------------------------------------------


def main(cfg) -> dict:
    import json

    from dgraph_tpu.obs.health import RunHealth
    from dgraph_tpu.utils import ExperimentLog

    health = RunHealth.begin("analysis.spmd.cli")
    log = ExperimentLog(cfg.log_path, echo=False)
    if cfg.selftest:
        out = spmd_selftest(log, seed=cfg.seed)
        failures = out["failures"]
    else:
        with tempfile.TemporaryDirectory(prefix="dgraph_spmd_") as tmp:
            build_spmd_fixture(cfg.world, tmp, seed=cfg.seed)
            out = audit_plan_dir_spmd(tmp)
        failures = out["failures"]
    out["run_health"] = health.finish(
        "; ".join(failures) if failures else None,
        wedge="stage_failure" if failures else None,
    )
    log.write(out)
    print(json.dumps(out, indent=cfg.indent or None))
    if failures:
        raise SystemExit("spmd audit FAILED: " + "; ".join(failures[:10]))
    return out


if __name__ == "__main__":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from dgraph_tpu.utils.cli import parse_config

    @dataclasses.dataclass
    class Config:
        """Cross-rank SPMD divergence auditor (``--selftest`` runs the
        2/4-shard + shrink-generation audits plus the seeded-divergence
        vacuity mutants; default audits a fresh ``--world`` fixture)."""

        selftest: bool = False
        world: int = 2
        seed: int = 0
        log_path: str = "logs/analysis.jsonl"
        indent: int = 0

    main(parse_config(Config))
