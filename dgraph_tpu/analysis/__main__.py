"""``python -m dgraph_tpu.analysis`` — static-analysis CLI: contract
linter + trace auditor + lowered-artifact (StableHLO) auditor + Pallas
DMA-discipline verifier + cross-rank SPMD divergence auditor + host-side
concurrency & durability auditor.

The host tier (``analysis.host``, ISSUE 15) audits the *other* program —
the jax-free concurrent control plane: per-class guarded-field/lock
discipline (races), the inter-class lock-acquisition-order graph
(deadlocks), atomic-writer routing for durable artifacts and the
pointer-flip-last commit contract (torn writes), and chaos-registry
coverage drift.  Its per-file rules run inside the lint pass (one
registry, one pragma); the repo-level graphs land in the report's
``host_audit`` section.

Default mode lints the whole ``dgraph_tpu`` tree and audits the canonical
2-shard workload under every halo lowering at ALL verification tiers —
the jaxpr-level trace audit, the post-lowering HLO audit, the
``pallas_p2p`` kernel DMA verifier, and the cross-rank SPMD audit (every
rank's program lowered from its own plan-shard-subset view and proven
identical, in identical collective order) — printing one JSON line and
exiting nonzero on any finding or drift; the pre-merge gate
``scripts/check.py`` wraps it.

``--selftest`` is the compile-free tier-1 registration: lint-rule fixture
checks (every rule must fire on a violating snippet and stay quiet on a
clean one), a clean-tree lint, the 2- AND 4-shard trace AND HLO audits
across all four halo lowerings (op counts + operand bytes pinned against
``obs.footprint`` at both tiers), the kernel audits, the cross-rank SPMD
audits (2- and 4-shard worlds plus both generations of a real
``train/shrink.py`` W -> W-1 transition), and vacuity guards proving each
tier still FAILS on seeded drift: a wrong lowering, wrong bytes, a mixed
program, a seeded extra all-gather, a dropped donation (declare- and
shape-level), a dropped ``dma_wait`` (plus the other kernel-discipline
mutants), a raw ``shard_map`` check kwarg, and the seeded SPMD
divergences (a rank-dependent branch dropping one ppermute round on rank
1, a swapped two-collective order, a rank-divergent tuned record).  Zero
XLA compiles: the jaxpr tier traces abstractly and the HLO/SPMD tiers
are lower-only (``jit(...).lower()``; jit-cache counters asserted — the
rule ``tests/README.md`` documents).

``--bench_fallback`` prints the compact ``schedule_drift`` record bench.py
attaches to its JSON when no healthy chip ever comes up (ROADMAP item 5's
non-null fallback tier); ``--fallback_kind hlo_drift`` /
``--fallback_kind spmd_drift`` select the lowered-artifact and cross-rank
drift records instead (bench attaches all of them).

``--list_rules`` prints the lint-rule registry (name, scope, description)
— the machine-readable source the rule-catalog table in
``docs/static-analysis.md`` is pinned against.

Every exit path carries a RunHealth record; reports stream to the JSONL
log (``--log_path``) via ExperimentLog.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import tempfile

# The audit traces multi-shard shard_map programs, which needs a multi-
# device (virtual CPU) backend.  jax is already IMPORTED here (the
# package __init__ pulls compat in) and freezes jax_platforms from the
# ambient env at import time, so the env pin alone is NOT enough — the
# jax.config.update below is what actually redirects a sitecustomize- or
# env-pinned TPU platform (same two-step as tests/conftest.py and
# scripts/gen_api_docs.py).  Analysis is a host-side static pass: it
# must never dial an accelerator, so the pin is unconditional.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


@dataclasses.dataclass
class Config:
    """Static analysis (``--selftest`` for the compile-free tier-1 smoke;
    ``--bench_fallback`` for the bench's fallback records —
    ``--fallback_kind hlo_drift`` selects the lowered-artifact tier)."""

    selftest: bool = False
    bench_fallback: bool = False
    fallback_kind: str = "schedule_drift"  # or "hlo_drift" / "spmd_drift"
    list_rules: bool = False  # print the lint-rule registry and exit
    lint: bool = True
    audit: bool = True
    hlo: bool = True     # lowered-artifact (StableHLO) tier
    kernel: bool = True  # pallas_p2p DMA-discipline tier
    spmd: bool = True    # cross-rank SPMD divergence tier
    host: bool = True    # host-side concurrency & durability tier
    root: str = ""  # lint root; "" = the repo containing this package
    world: int = 2  # audit world size (default mode)
    # bench-fallback workload shape (a reduced arxiv-like graph: the
    # drift signal is structural — op counts and byte ratios — so it does
    # not need the full 169k-node build on a wedged round's clock)
    nodes: int = 4096
    edges: int = 16384
    feat_dim: int = 32
    seed: int = 0
    log_path: str = "logs/analysis.jsonl"
    indent: int = 0


# ---------------------------------------------------------------------------
# lint-rule fixtures: every rule must fire on `bad` and not on `good`
# ---------------------------------------------------------------------------

_FIXTURES = {
    "jax-free-module": {
        "path": "dgraph_tpu/chaos/__init__.py",
        "bad": "def poison(tree):\n    import jax\n    return jax.tree.map(id, tree)\n",
        "good": "import os\n\ndef poison(tree):\n    return tree\n",
    },
    "no-config-read-in-trace": {
        "path": "dgraph_tpu/comm/collectives.py",
        "bad": (
            "from dgraph_tpu import config as _cfg\n"
            "import jax\n"
            "def step(x):\n"
            "    def body(y):\n"
            "        return y if _cfg.halo_impl == 'auto' else -y\n"
            "    return jax.jit(body)(x)\n"
        ),
        "good": (
            "from dgraph_tpu import config as _cfg\n"
            "import jax\n"
            "def step(x):\n"
            "    impl = _cfg.halo_impl\n"
            "    def body(y):\n"
            "        return y if impl == 'auto' else -y\n"
            "    return jax.jit(body)(x)\n"
        ),
    },
    "no-span-in-trace": {
        "path": "dgraph_tpu/train/loop.py",
        "bad": (
            "import jax\n"
            "from dgraph_tpu.obs import spans\n"
            "def step(x):\n"
            "    def body(y):\n"
            "        with spans.span('inner', stage='agg'):\n"
            "            return y * 2\n"
            "    return jax.jit(body)(x)\n"
        ),
        "good": (
            "import jax\n"
            "from dgraph_tpu.obs import spans\n"
            "def step(x):\n"
            "    with spans.span('outer', stage='step'):\n"
            "        return jax.jit(lambda y: y * 2)(x)\n"
        ),
    },
    "custom-vjp-paired": {
        "path": "dgraph_tpu/ops/local.py",
        "bad": (
            "import jax\n"
            "@jax.custom_vjp\n"
            "def f(x):\n"
            "    return x\n"
        ),
        "good": (
            "import jax\n"
            "@jax.custom_vjp\n"
            "def f(x):\n"
            "    return x\n"
            "f.defvjp(lambda x: (x, None), lambda r, g: (g,))\n"
        ),
    },
    "named-scope-on-collectives": {
        "path": "dgraph_tpu/comm/collectives.py",
        "bad": (
            "from jax import lax\n"
            "def exchange(x, axis):\n"
            "    return lax.all_to_all(x, axis, 0, 0)\n"
        ),
        "good": (
            "from jax import lax\n"
            "@_scoped('dgraph.exchange')\n"
            "def exchange(x, axis):\n"
            "    return lax.all_to_all(x, axis, 0, 0)\n"
        ),
    },
    "no-monolithic-plan-pickle": {
        "path": "dgraph_tpu/train/checkpoint.py",
        "bad": (
            "from dgraph_tpu.train.checkpoint import atomic_pickle_dump\n"
            "def cache(path, edge_index, part):\n"
            "    from dgraph_tpu.plan import build_edge_plan\n"
            "    plan = build_edge_plan(edge_index, part)\n"
            "    atomic_pickle_dump(path, plan)\n"
        ),
        "good": (
            "from dgraph_tpu.train.checkpoint import atomic_pickle_dump\n"
            "def save(path, step, params):\n"
            "    atomic_pickle_dump(path, {'step': step, 'params': params})\n"
        ),
    },
    "no-nondeterminism-in-plan": {
        "path": "dgraph_tpu/plan.py",
        "bad": (
            "import numpy as np\n"
            "def build(edges):\n"
            "    perm = np.random.permutation(len(edges))\n"
            "    return edges[perm]\n"
        ),
        "good": (
            "import numpy as np\n"
            "def build(edges, seed):\n"
            "    rng = np.random.default_rng(seed)\n"
            "    return edges[rng.permutation(len(edges))]\n"
        ),
    },
    # trace-time SPMD divergence at its source: a rank read steering
    # PYTHON control flow in a traced body hands every rank a different
    # program (the deadlock class analysis.spmd audits at the artifact
    # level). Host-side rank reads OUTSIDE the traced boundary are the
    # sanctioned pattern (checkpoint dirs, leader logging).
    "no-rank-branch-in-trace": {
        "path": "dgraph_tpu/train/loop.py",
        "bad": (
            "import jax\n"
            "def step(x):\n"
            "    def body(y):\n"
            "        if jax.process_index() == 0:\n"
            "            return y * 2\n"
            "        return y\n"
            "    return jax.jit(body)(x)\n"
        ),
        "good": (
            "import jax\n"
            "def launch(x):\n"
            "    if jax.process_index() == 0:\n"
            "        print('leader owns the checkpoint dir')\n"
            "    return jax.jit(lambda y: y * 2)(x)\n"
        ),
    },
    # an ad-hoc narrowing cast next to a collective ships bytes the
    # footprint/trace/tuner pipeline never prices; the sanctioned shape
    # is the wire codec pair (encode before the exchange, decode after,
    # both priced). Casting to x.dtype (no literal) stays green.
    "no-unpriced-wire-cast": {
        "path": "dgraph_tpu/comm/collectives.py",
        "bad": (
            "from jax import lax\n"
            "def exchange(x, axis):\n"
            "    send = x.astype('bfloat16')\n"
            "    return lax.all_to_all(send, axis, 0, 0)\n"
        ),
        "good": (
            "from jax import lax\n"
            "from dgraph_tpu.wire.codec import make_wire_transform\n"
            "def exchange(x, axis, enc, dec):\n"
            "    recv = lax.all_to_all(enc(x), axis, 0, 0)\n"
            "    return dec(recv).astype(x.dtype)\n"
        ),
    },
}

# the rank-env spelling of the same divergence (os.environ[RANK_ENV_VAR]
# slicing a traced operand) must fire too — and the pragma must suppress
# it like any other rule
_RANK_ENV_BRANCH_BAD = (
    "import os\n"
    "import jax\n"
    "from dgraph_tpu.utils.env import RANK_ENV_VAR\n"
    "def step(x):\n"
    "    def body(y):\n"
    "        r = int(os.environ[RANK_ENV_VAR])\n"
    "        return y[r:]\n"
    "    return jax.jit(body)(x)\n"
)


# the pallas_p2p kernel module gets its own fixture pair per trace-
# discipline rule: the one-sided transport is the newest place a config
# read or span could sneak inside traced code, so the rules must
# demonstrably fire (and stay quiet) on that path too
_P2P_FIXTURES = {
    "no-config-read-in-trace": {
        "path": "dgraph_tpu/ops/pallas_p2p.py",
        "bad": (
            "from dgraph_tpu import config as _cfg\n"
            "import jax\n"
            "def p2p_transport(x):\n"
            "    def body(y):\n"
            "        return y if _cfg.use_pallas_p2p else -y\n"
            "    return jax.jit(body)(x)\n"
        ),
        "good": (
            "from dgraph_tpu import config as _cfg\n"
            "import jax\n"
            "def p2p_transport(x):\n"
            "    interpret = _cfg.pallas_p2p_available()\n"
            "    def body(y):\n"
            "        return y if interpret else -y\n"
            "    return jax.jit(body)(x)\n"
        ),
    },
    "no-span-in-trace": {
        "path": "dgraph_tpu/ops/pallas_p2p.py",
        "bad": (
            "import jax\n"
            "from dgraph_tpu.obs import spans\n"
            "def p2p_transport(x):\n"
            "    def body(y):\n"
            "        with spans.span('p2p.put', stage='exchange'):\n"
            "            return y * 2\n"
            "    return jax.jit(body)(x)\n"
        ),
        "good": (
            "import jax\n"
            "from dgraph_tpu.obs import spans\n"
            "def p2p_transport(x):\n"
            "    with spans.span('p2p.transport', stage='exchange'):\n"
            "        return jax.jit(lambda y: y * 2)(x)\n"
        ),
    },
}


# pallas_call kernel bodies are traced code too — until ISSUE 12 they
# were the trace-discipline rules' blind spot (kernels reach pallas_call
# through a functools.partial alias, which the descent now sees through)
_KERNEL_FIXTURES = {
    "no-config-read-in-trace": {
        "path": "dgraph_tpu/ops/pallas_p2p.py",
        "bad": (
            "import functools\n"
            "from jax.experimental import pallas as pl\n"
            "from dgraph_tpu import config as _cfg\n"
            "def _kernel(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...] * (2 if _cfg.use_pallas_p2p else 1)\n"
            "def transport(x, shape):\n"
            "    kern = functools.partial(_kernel)\n"
            "    return pl.pallas_call(kern, out_shape=shape)(x)\n"
        ),
        "good": (
            "import functools\n"
            "from jax.experimental import pallas as pl\n"
            "from dgraph_tpu import config as _cfg\n"
            "def _kernel(x_ref, o_ref, *, scale):\n"
            "    o_ref[...] = x_ref[...] * scale\n"
            "def transport(x, shape):\n"
            "    scale = 2 if _cfg.use_pallas_p2p else 1\n"
            "    kern = functools.partial(_kernel, scale=scale)\n"
            "    return pl.pallas_call(kern, out_shape=shape)(x)\n"
        ),
    },
    "no-span-in-trace": {
        "path": "dgraph_tpu/ops/pallas_p2p.py",
        "bad": (
            "import functools\n"
            "from jax.experimental import pallas as pl\n"
            "from dgraph_tpu.obs import spans\n"
            "def _kernel(x_ref, o_ref):\n"
            "    with spans.span('p2p.tile', stage='exchange'):\n"
            "        o_ref[...] = x_ref[...]\n"
            "def transport(x, shape):\n"
            "    kern = functools.partial(_kernel)\n"
            "    return pl.pallas_call(kern, out_shape=shape)(x)\n"
        ),
        "good": (
            "import functools\n"
            "from jax.experimental import pallas as pl\n"
            "from dgraph_tpu.obs import spans\n"
            "def _kernel(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def transport(x, shape):\n"
            "    with spans.span('p2p.transport', stage='exchange'):\n"
            "        kern = functools.partial(_kernel)\n"
            "        return pl.pallas_call(kern, out_shape=shape)(x)\n"
        ),
    },
}


_SHARD_MAP_FIXTURES = {
    "no-unchecked-shard-map": {
        "path": "dgraph_tpu/train/loop.py",
        "bad": (
            "import jax\n"
            "def build(body, mesh, specs):\n"
            "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
            "                         out_specs=specs, check_vma=False)\n"
        ),
        "good": (
            "import jax\n"
            "from dgraph_tpu.comm.collectives import shard_map_checks\n"
            "def build(body, mesh, specs, plan):\n"
            "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
            "                         out_specs=specs,\n"
            "                         **shard_map_checks(plan, 'graph'))\n"
        ),
    },
}

# the RELAXED_CHECKS splat spelling must fire too (the blanket escape
# parallel/sequence.py carried before its ISSUE 12 audit)
_SHARD_MAP_SPLAT_BAD = (
    "import jax\n"
    "from dgraph_tpu import compat as _compat\n"
    "def build(body, mesh, specs):\n"
    "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
    "                         out_specs=specs, **_compat.RELAXED_CHECKS)\n"
)


def _check(failures, cond, msg):
    if not cond:
        failures.append(msg)


def _lint_fixture_checks(failures: list) -> None:
    from dgraph_tpu.analysis import lint as L

    fixture_sets = (
        list(_FIXTURES.items())
        + list(_P2P_FIXTURES.items())
        + list(_KERNEL_FIXTURES.items())
        + list(_SHARD_MAP_FIXTURES.items())
    )
    for name, fx in fixture_sets:
        rule = L.RULES[name]
        for kind, src in (("bad", fx["bad"]), ("good", fx["good"])):
            tree = ast.parse(src)
            lines = src.splitlines()
            if name == "jax-free-module":
                got = rule.check(fx["path"], tree, lines, root="")
            else:
                got = rule.check(fx["path"], tree, lines)
            if kind == "bad":
                _check(
                    failures, got,
                    f"rule {name!r} missed its fixture ({fx['path']})",
                )
            else:
                _check(
                    failures, not got,
                    f"rule {name!r} false-positived on clean code "
                    f"({fx['path']}): {got}",
                )
    # the **RELAXED_CHECKS splat spelling of an unchecked shard_map must
    # fire too (keyword fixture above covers check_vma=)
    got = L.RULES["no-unchecked-shard-map"].check(
        "dgraph_tpu/parallel/sequence.py",
        ast.parse(_SHARD_MAP_SPLAT_BAD),
        _SHARD_MAP_SPLAT_BAD.splitlines(),
    )
    _check(
        failures, got,
        "no-unchecked-shard-map missed a **RELAXED_CHECKS splat",
    )
    # the rank-env slicing spelling of trace-time SPMD divergence
    got = L.RULES["no-rank-branch-in-trace"].check(
        "dgraph_tpu/train/loop.py",
        ast.parse(_RANK_ENV_BRANCH_BAD),
        _RANK_ENV_BRANCH_BAD.splitlines(),
    )
    _check(
        failures, got,
        "no-rank-branch-in-trace missed an os.environ[RANK_ENV_VAR] "
        "slice in a traced body",
    )
    # pragma suppression: the bad jax-free fixture goes quiet when allowed
    src = "def poison(tree):\n    import jax  # lint: allow(jax-free-module)\n"
    got = L.RULES["jax-free-module"].check(
        "dgraph_tpu/chaos/__init__.py", ast.parse(src), src.splitlines(),
        root="",
    )
    got = [
        f for f in got
        if not L._suppressed(src.splitlines(), f.line, f.rule)
    ]
    _check(failures, not got, "pragma did not suppress a finding")
    # ...and the wire-cast rule honors the same pragma (an allowed cast
    # is a documented, greppable decision, e.g. a diagnostic-only path)
    src = (
        "from jax import lax\n"
        "def exchange(x, axis):\n"
        "    send = x.astype('bfloat16')  # lint: allow(no-unpriced-wire-cast)\n"
        "    return lax.all_to_all(send, axis, 0, 0)\n"
    )
    got = L.RULES["no-unpriced-wire-cast"].check(
        "dgraph_tpu/comm/collectives.py", ast.parse(src), src.splitlines(),
    )
    got = [
        f for f in got
        if not L._suppressed(src.splitlines(), f.line, f.rule)
    ]
    _check(
        failures, not got,
        "pragma did not suppress a no-unpriced-wire-cast finding",
    )
    # transitive module-level check: importing a dgraph_tpu module that
    # itself imports jax at module level must fire
    with tempfile.TemporaryDirectory(prefix="dgraph_lint_selftest_") as tmp:
        os.makedirs(os.path.join(tmp, "dgraph_tpu", "chaos"))
        with open(os.path.join(tmp, "dgraph_tpu", "helper.py"), "w") as fh:
            fh.write("import jax\n")
        target = os.path.join(tmp, "dgraph_tpu", "chaos", "__init__.py")
        with open(target, "w") as fh:
            fh.write("from dgraph_tpu.helper import thing\n")
        got = L.lint_file(target, tmp)
        _check(
            failures,
            any(f.rule == "jax-free-module" for f in got),
            "transitive jax-free-module check missed a jax-using import",
        )


def _audit_vacuity_checks(failures: list, w2, w4) -> None:
    """The auditor must still FAIL on real drift — a green audit is only
    evidence if these reds stay red."""
    from dgraph_tpu import config as _cfg
    from dgraph_tpu.analysis import trace as T

    # wrong lowering family: a ppermute-pinned program audited as
    # all_to_all must fail
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl)
    try:
        _cfg.set_flags(halo_impl="ppermute", tuned_halo_impl=None)
        fn, args = T._train_program(w2)
        mism: list = []
        T._audit_one_program("vacuity", "all_to_all", fn, args, w2.plan_np, mism)
        _check(failures, mism, "auditor accepted a mismatched lowering family")

        # wrong bytes: auditing the 2-shard trace against the 4-shard
        # plan's footprint must fail on operand bytes
        fn, args = T._train_program(w2)
        mism = []
        T._audit_one_program("vacuity", "ppermute", fn, args, w4.plan_np, mism)
        _check(
            failures, mism,
            "auditor accepted operand bytes from the wrong plan",
        )
    finally:
        _cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])

    # mixed pallas_p2p + ppermute legs in ONE program must stay RED in
    # the one-family audit: the exchange lowered as one-sided puts but
    # its reverse leg as ppermute rounds is exactly the PR 4 hazard in
    # its newest costume
    import jax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import collectives
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    def mixed(xs, plan):
        def body(plan_, x):
            p = squeeze_plan(plan_)
            buf = collectives.halo_exchange(
                x[0], p.halo, GRAPH_AXIS, deltas=p.halo_deltas,
                impl="pallas_p2p",
            )
            back = collectives.halo_scatter_sum(
                buf, p.halo, p.n_src_pad, GRAPH_AXIS,
                deltas=p.halo_deltas, impl="ppermute",
            )
            return back[None]

        return jax.shard_map(
            body, mesh=w2.mesh,
            in_specs=(plan_in_specs(w2.plan), P(GRAPH_AXIS)),
            out_specs=P(GRAPH_AXIS),
            **collectives.shard_map_checks(impl="pallas_p2p"),
        )(plan, xs)

    mism = []
    T._audit_one_program(
        "vacuity-mixed", "pallas_p2p", mixed,
        (w2.batch["x"], w2.plan), w2.plan_np, mism,
    )
    _check(
        failures,
        any("mixed halo lowerings" in m for m in mism),
        "auditor accepted a program mixing pallas_p2p puts with a "
        "ppermute leg",
    )

    # dropped donation: a step that returns only metrics must report the
    # params/opt_state donations unmatched
    fn, args = T._train_program(w2)
    dropped = lambda p, o, b, pl: fn(p, o, b, pl)[2]  # noqa: E731
    unmatched = T.donation_unmatched(dropped, args, (w2.params, w2.opt_state))
    _check(failures, unmatched, "donation check missed dropped buffers")


def _hlo_vacuity_checks(failures: list, w2) -> None:
    """The lowered-artifact auditor must still FAIL on seeded drift: an
    extra XLA-level all-gather, a dropped donation (both the declare-level
    drop and the shape-uncovered drop), and a wrong lowering family —
    the reds that make the HLO tier's green mean something."""
    import warnings

    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import config as _cfg
    from dgraph_tpu.analysis import hlo as H
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.comm.mesh import GRAPH_AXIS
    from dgraph_tpu.train.loop import make_train_step

    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl)
    try:
        _cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl=None)
        fn, args = _train_program(w2)

        # seeded extra all-gather: the accidental-collective class the
        # relaxed rep checker can no longer catch must go RED at the
        # artifact level
        def seeded(params, opt_state, batch, plan):
            out = fn(params, opt_state, batch, plan)
            extra = jax.shard_map(
                lambda x: lax.all_gather(x[0], GRAPH_AXIS),
                mesh=w2.mesh, in_specs=(P(GRAPH_AXIS),), out_specs=P(),
                **shard_map_checks(relax="seeded vacuity mutant"),
            )(batch["x"])
            return out, extra

        mism: list = []
        H._audit_one_lowering(
            "vacuity-extra-ag", "all_to_all",
            H.lower_program(jax.jit(seeded, donate_argnums=(0, 1)), args),
            w2.plan_np, w2.mesh, mism,
        )
        _check(
            failures,
            any("unscheduled all_gather" in m for m in mism),
            "HLO auditor accepted an XLA-materialized all_gather the plan "
            "never scheduled",
        )

        # dropped donation (declare level): donate=False must leave zero
        # donor entries in the lowered module
        donated = len(jax.tree.leaves((w2.params, w2.opt_state)))
        nd = make_train_step(
            w2.model, w2.optimizer, w2.mesh, w2.plan, donate=False
        )
        mism = []
        H._donation_failures(
            H.donation_entries(H.lower_program(nd, args)), donated,
            "vacuity-no-donate", mism,
        )
        _check(failures, mism, "HLO auditor missed a dropped donation")

        # dropped donation (shape level): a metrics-only step donates
        # buffers no output can cover — XLA would silently drop the alias
        mo = jax.jit(
            lambda p, o, b, pl: fn(p, o, b, pl)[2], donate_argnums=(0, 1)
        )
        mism = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # jax warns on unusable donations
            H._donation_failures(
                H.donation_entries(H.lower_program(mo, args)), donated,
                "vacuity-uncovered", mism,
            )
        _check(
            failures, mism,
            "HLO auditor missed a donation no output type covers",
        )

        # wrong lowering family at the artifact level
        _cfg.set_flags(halo_impl="ppermute", tuned_halo_impl=None)
        fn2, args2 = _train_program(w2)
        mism = []
        H._audit_one_lowering(
            "vacuity-family", "all_to_all", H.lower_program(fn2, args2),
            w2.plan_np, w2.mesh, mism,
        )
        _check(
            failures, mism,
            "HLO auditor accepted a mismatched lowering family",
        )
    finally:
        _cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def _selftest(cfg: Config, log) -> dict:
    from dgraph_tpu.analysis.hlo import audit_workload_hlo
    from dgraph_tpu.analysis.kernel import (
        audit_workload_kernels, kernel_selftest_failures,
    )
    from dgraph_tpu.analysis.lint import run_lint
    from dgraph_tpu.analysis.trace import audit_workload, build_audit_workload

    failures: list = []
    _lint_fixture_checks(failures)

    tree_report = run_lint(cfg.root or None)
    _check(
        failures, tree_report["ok"],
        f"tree lint found violations: {tree_report['findings']}",
    )

    audits = {}
    hlo_audits = {}
    workloads = {}
    for world in (2, 4):
        w = build_audit_workload(world, seed=cfg.seed)
        workloads[world] = w
        rep = audit_workload(w)
        audits[world] = rep
        log.write(rep)
        _check(
            failures, rep["ok"],
            f"{world}-shard trace audit drifted: {rep['failures']}",
        )
        _check(
            failures, rep["num_halo_deltas"] >= 1,
            f"{world}-shard audit graph has no cross-rank traffic "
            f"(the byte pins would be vacuous)",
        )
        # the lowered-artifact tier: same workloads, one level down —
        # lower-only (jit(...).lower(); still zero XLA compiles)
        hrep = audit_workload_hlo(w)
        hlo_audits[world] = hrep
        log.write(hrep)
        _check(
            failures, hrep["ok"],
            f"{world}-shard HLO audit drifted: {hrep['failures']}",
        )
        # the DMA-discipline tier over the real transports
        krep = audit_workload_kernels(w)
        log.write(krep)
        _check(
            failures, krep["ok"],
            f"{world}-shard kernel audit failed: {krep['failures']}",
        )

    _audit_vacuity_checks(failures, workloads[2], workloads[4])
    _hlo_vacuity_checks(failures, workloads[2])
    # kernel-verifier vacuity: the seeded kernel mutations (dropped
    # dma_wait among them) must each go RED
    failures.extend(kernel_selftest_failures())

    # the cross-rank SPMD tier: 2- and 4-shard worlds, both generations
    # of a real W -> W-1 shrink, and the seeded-divergence mutants —
    # lower-only, jit-cache counters ride the spmd summary
    from dgraph_tpu.analysis.spmd import spmd_selftest

    spmd_summary = spmd_selftest(log, seed=cfg.seed)
    failures.extend(spmd_summary.pop("failures"))

    # the host-side concurrency & durability tier: per-rule fixture
    # pairs + the vacuity mutants (unlocked guarded-field write, seeded
    # lock-order cycle, bare-open manifest write, pointer-flip-before-
    # payload, unregistered chaos fire site — each must go RED) + the
    # clean-tree audit — pure stdlib ast, zero compiles by construction
    from dgraph_tpu.analysis.host import (
        host_selftest_failures, run_host_audit,
    )

    failures.extend(host_selftest_failures(cfg.root or None))
    host_audit = run_host_audit(cfg.root or None)
    log.write(host_audit)

    return {
        "kind": "analysis_selftest",
        "failures": failures,
        "lint_files_checked": tree_report["files_checked"],
        "audit": {
            str(wld): {
                "ok": rep["ok"],
                "exchange_legs": rep["exchange_legs"],
                "num_halo_deltas": rep["num_halo_deltas"],
            }
            for wld, rep in audits.items()
        },
        "hlo_audit": {
            str(wld): {
                "ok": rep["ok"],
                "exchange_legs": rep["exchange_legs"],
                "donation": rep["donation"],
            }
            for wld, rep in hlo_audits.items()
        },
        "host_audit": {
            "ok": host_audit["ok"],
            "files_checked": host_audit["files_checked"],
            "lock_edges": host_audit["lock_edges"],
            "chaos_points": host_audit["chaos_points"],
        },
        "spmd_audit": spmd_summary,
    }


def main(cfg: Config) -> dict:
    from dgraph_tpu.obs.health import RunHealth
    from dgraph_tpu.utils import ExperimentLog

    health = RunHealth.begin("analysis.cli")
    log = ExperimentLog(cfg.log_path, echo=False)
    try:
        if cfg.list_rules:
            from dgraph_tpu.analysis.lint import RULES

            out = {
                "kind": "rule_catalog",
                "rules": [
                    {"name": r.name, "scope": r.scope,
                     "description": r.description}
                    for r in sorted(RULES.values(), key=lambda r: r.name)
                ],
            }
            print(json.dumps(out, indent=cfg.indent or None))
            return out
        if cfg.bench_fallback:
            if cfg.fallback_kind == "hlo_drift":
                from dgraph_tpu.analysis.hlo import hlo_drift_record

                out = hlo_drift_record(
                    8, num_nodes=cfg.nodes, num_edges=cfg.edges,
                    feat_dim=cfg.feat_dim, seed=cfg.seed,
                )
            elif cfg.fallback_kind == "spmd_drift":
                from dgraph_tpu.analysis.spmd import spmd_drift_record

                # cross-rank identity is per-rank-lowering-heavy; a
                # reduced shape keeps the wedged round's budget (the
                # signal — do the ranks agree at all — is structural)
                out = spmd_drift_record(
                    4, num_nodes=min(cfg.nodes, 1024),
                    num_edges=min(cfg.edges, 4096),
                    feat_dim=cfg.feat_dim, seed=cfg.seed,
                )
            else:
                from dgraph_tpu.analysis.trace import schedule_drift_record

                out = schedule_drift_record(
                    8, num_nodes=cfg.nodes, num_edges=cfg.edges,
                    feat_dim=cfg.feat_dim, seed=cfg.seed,
                )
            out["run_health"] = health.finish(
                "; ".join(out["failures"]) if out["drift"] else None,
                wedge="stage_failure" if out["drift"] else None,
            )
            log.write(out)
            print(json.dumps(out, indent=cfg.indent or None))
            return out
        if cfg.selftest:
            out = _selftest(cfg, log)
            failures = out["failures"]
            out["run_health"] = health.finish(
                "; ".join(failures) if failures else None,
                wedge="stage_failure" if failures else None,
            )
            log.write(out)
            print(json.dumps(out, indent=cfg.indent or None))
            if failures:
                raise SystemExit(
                    "analysis selftest FAILED: " + "; ".join(failures)
                )
            return out

        problems: list = []
        out = {"kind": "analysis_report"}
        if cfg.lint:
            from dgraph_tpu.analysis.lint import run_lint

            lint_report = run_lint(cfg.root or None)
            out["lint"] = lint_report
            if not lint_report["ok"]:
                problems.extend(
                    f"{f['rule']} {f['path']}:{f['line']}"
                    for f in lint_report["findings"]
                )
        if cfg.audit or cfg.hlo or cfg.kernel:
            from dgraph_tpu.analysis.trace import build_audit_workload

            w = build_audit_workload(cfg.world, seed=cfg.seed)
        if cfg.audit:
            from dgraph_tpu.analysis.trace import audit_workload

            audit_report = audit_workload(w)
            out["audit"] = audit_report
            problems.extend(audit_report["failures"])
        if cfg.hlo:
            from dgraph_tpu.analysis.hlo import audit_workload_hlo

            hlo_report = audit_workload_hlo(w)
            out["hlo_audit"] = hlo_report
            problems.extend(hlo_report["failures"])
        if cfg.kernel:
            from dgraph_tpu.analysis.kernel import audit_workload_kernels

            kernel_report = audit_workload_kernels(w)
            out["kernel_audit"] = kernel_report
            problems.extend(kernel_report["failures"])
        if cfg.host:
            # host-side concurrency & durability tier: the per-FILE host
            # rules (lock discipline, durable writes, pointer-flip-last)
            # already ran in the lint pass above — this section adds the
            # repo-level graphs (lock-acquisition order, chaos-registry
            # coverage) plus the structural summary
            from dgraph_tpu.analysis.host import run_host_audit

            host_report = run_host_audit(
                cfg.root or None, file_rules=not cfg.lint
            )
            out["host_audit"] = host_report
            problems.extend(host_report["failures"])
        if cfg.spmd:
            from dgraph_tpu.analysis.spmd import (
                audit_plan_dir_spmd, build_spmd_fixture,
            )

            with tempfile.TemporaryDirectory(
                prefix="dgraph_spmd_cli_"
            ) as tmp:
                build_spmd_fixture(cfg.world, tmp, seed=cfg.seed)
                spmd_report = audit_plan_dir_spmd(tmp)
            out["spmd_audit"] = spmd_report
            problems.extend(spmd_report["failures"])
        out["ok"] = not problems
        out["run_health"] = health.finish(
            "; ".join(problems) if problems else None,
            wedge="stage_failure" if problems else None,
        )
        log.write(out)
        print(json.dumps(out, indent=cfg.indent or None))
        if problems:
            raise SystemExit("analysis FAILED: " + "; ".join(problems[:10]))
        return out
    except SystemExit:
        raise
    except BaseException as e:  # every exit path carries a RunHealth record
        log.write({
            "kind": "run_health",
            **health.finish(
                f"analysis failed: {type(e).__name__}: {e}",
                wedge="interrupted"
                if isinstance(e, KeyboardInterrupt) else "stage_failure",
            ),
        })
        raise


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
