"""Lowered-artifact auditor: verify the StableHLO the programs actually
lower to — collective schedule, operand bytes, and donation survival.

The trace auditor (:mod:`dgraph_tpu.analysis.trace`) stops at the jaxpr:
it proves the *traced* program emits the collective schedule
``obs.footprint`` prices. But the artifact XLA compiles is one level
lower, and two things can change between jaxpr and StableHLO:

- **XLA-materialized collectives.** ``pallas_p2p`` programs relax the
  jax-0.4.x shard_map replication checker (``compat.RELAXED_CHECKS``), so
  a wrong out-spec can make the partitioner insert a full ``all_gather``
  that no jaxpr-level check sees — the exact hazard the relaxation
  re-opened (GC3 in PAPERS.md treats the compiled collective schedule as
  an artifact to verify, not hope about).
- **Donation.** ``donate_argnums`` is jit metadata at the jaxpr level;
  whether it survives is decided at lowering, where each honored donation
  becomes a ``jax.buffer_donor`` / ``tf.aliasing_output`` entry on a
  ``main`` argument. A dropped donation (an output shape drifted away
  from its donated input) costs the full params+opt_state footprint of
  peak HBM and raises no error anywhere.

So this tier lowers every (program, halo lowering) pair with
``jit(...).lower()`` — **lower-only, never ``.compile()``**: StableHLO
emission is a host-side MLIR build, zero XLA compiles, zero device
buffers (the rule ``tests/README.md`` documents) — and walks the module:

- collective op kinds/counts match the planned schedule (``all_to_all``
  count == exchange legs; ``collective_permute`` count == legs *
  num_halo_deltas; ``pallas_p2p``'s interpret-mode DMA discharge ==
  exactly one tile-shaped ``all_gather`` plus two scalar index gathers
  per remote put);
- ``replica_groups`` / ``source_target_pairs`` are exactly the graph-axis
  groups / live-delta rings the plan schedules;
- per-operand bytes equal ``obs.footprint``'s pricing at the LOWERED
  width/dtype (the numbers the tuner ranks on, re-pinned below the
  jaxpr);
- **no collective the plan didn't schedule** — any other ``all_gather``
  / ``reduce_scatter`` / ``collective_broadcast``, or a second transport
  family in one program, is drift;
- no ``all_reduce`` on a sub-32-bit dtype (fp32 accumulation at the
  artifact level);
- donation survives lowering (donor-entry count == donated leaves, and
  every donor argument's type is covered by an output type, so XLA can
  actually alias it).

Everything here assumes the virtual-CPU backend the analysis CLI pins
(``pallas_p2p`` kernels lower through the Pallas interpret-mode DMA
discharge there); the per-put all_gather census is that discharge's
artifact shape, pinned by the selftest's vacuity guards.
"""

from __future__ import annotations

import math
from typing import Optional

from dgraph_tpu.analysis.trace import (
    HALO_IMPLS,
    PROGRAMS,
    _expected_bytes,
    build_audit_workload,
)

__all__ = [
    "collect_stablehlo",
    "lower_program",
    "audit_workload_hlo",
    "donation_entries",
    "hlo_drift_record",
]

# StableHLO ops that move data across devices; anything here that the
# plan didn't schedule is drift
COLLECTIVE_HLO_OPS = (
    "all_to_all",
    "collective_permute",
    "all_gather",
    "all_reduce",
    "reduce_scatter",
    "collective_broadcast",
)

# MLIR element type -> (numpy-ish dtype name, itemsize)
_MLIR_DTYPES = {
    "f64": ("float64", 8), "f32": ("float32", 4),
    "bf16": ("bfloat16", 2), "f16": ("float16", 2),
    "i64": ("int64", 8), "i32": ("int32", 4),
    "i16": ("int16", 2), "i8": ("int8", 1), "i1": ("bool", 1),
    "ui64": ("uint64", 8), "ui32": ("uint32", 4), "ui8": ("uint8", 1),
    # fp8 wire payloads ride collectives bitcast to ui8, but the e4m3
    # element type itself can appear in surrounding compute
    "f8E4M3FN": ("float8_e4m3fn", 1),
}

# interpret-mode DMA discharge artifact shape: per remote put, the
# compat discharge rule all-gathers the tile payload once and two i32
# scalars (the raveled device id and the landing-row index) — anything
# gathered beyond this budget per put was NOT scheduled by the plan
_DMA_ARTIFACT_INT_GATHERS_PER_PUT = 2
_DMA_ARTIFACT_INT_GATHER_MAX_BYTES = 32


def _elt_info(elt: str) -> tuple:
    return _MLIR_DTYPES.get(elt, (elt, 0))


def lower_program(fn, args):
    """``jit(...).lower`` the program — the ONE sanctioned way to produce
    the artifact this tier audits. ``fn`` must already be jitted (every
    registered program builder returns a jitted callable); the call never
    compiles and never touches a device buffer."""
    if not hasattr(fn, "lower"):
        raise TypeError(
            f"HLO audit needs a jitted program (got {type(fn).__name__}); "
            f"the registered builders return jit-wrapped steps precisely "
            f"so this tier can lower them without compiling"
        )
    return fn.lower(*args)


def _dense_2d(attr) -> Optional[list]:
    """DenseIntElementsAttr -> list of rows (replica_groups /
    source_target_pairs are always rank-2)."""
    from jaxlib.mlir import ir

    if attr is None:
        return None
    dense = ir.DenseIntElementsAttr(attr)
    shape = ir.ShapedType(dense.type).shape
    vals = list(dense)
    if len(shape) != 2:
        return [vals]
    it = iter(vals)
    return [[next(it) for _ in range(shape[1])] for _ in range(shape[0])]


def collect_stablehlo(lowered) -> dict:
    """One recursive walk over the lowered StableHLO module: every
    collective op (operand shape/dtype/bytes + replica_groups /
    source_target_pairs) and the ``main`` function's donation entries
    (``jax.buffer_donor`` / ``tf.aliasing_output`` argument attributes)
    and result types."""
    from jaxlib.mlir import ir

    module = lowered.compiler_ir(dialect="stablehlo")
    out = {k: [] for k in COLLECTIVE_HLO_OPS}
    donation = {"donor_args": [], "alias_args": 0, "result_types": []}

    def tensor_info(t):
        rt = ir.RankedTensorType(t)
        shape = tuple(int(s) for s in rt.shape)
        elt = str(rt.element_type)
        np_dtype, nbytes = _elt_info(elt)
        return shape, elt, np_dtype, int(math.prod(shape)) * nbytes

    def visit(op):
        name = op.name
        if name == "func.func":
            sym = ir.StringAttr(op.attributes["sym_name"]).value
            if sym == "main":
                ftype = ir.FunctionType(
                    ir.TypeAttr(op.attributes["function_type"]).value
                )
                donation["result_types"] = [
                    tensor_info(t)[:2] for t in ftype.results
                ]
                if "arg_attrs" in op.attributes:
                    args = ir.ArrayAttr(op.attributes["arg_attrs"])
                    for i, d in enumerate(args):
                        dd = ir.DictAttr(d)
                        if "tf.aliasing_output" in dd:
                            donation["alias_args"] += 1
                        elif "jax.buffer_donor" in dd:
                            donation["donor_args"].append(
                                tensor_info(ftype.inputs[i])[:2]
                            )
        elif name.startswith("stablehlo."):
            kind = name[len("stablehlo."):]
            if kind in out and op.operands:
                shape, elt, np_dtype, nbytes = tensor_info(
                    op.operands[0].type
                )
                attrs = {a.name: a.attr for a in op.attributes}
                out[kind].append({
                    "op": kind,
                    "shape": shape,
                    "dtype": np_dtype,
                    "elt": elt,
                    "bytes": nbytes,
                    "replica_groups": _dense_2d(attrs.get("replica_groups")),
                    "source_target_pairs": _dense_2d(
                        attrs.get("source_target_pairs")
                    ),
                })
        for region in op.regions:
            for block in region.blocks:
                for child in block.operations:
                    visit(child.operation)

    visit(module.operation)
    out["donation"] = donation
    return out


def donation_entries(lowered) -> dict:
    """Just the donation slice of :func:`collect_stablehlo` (for callers
    that only need the donor/alias census)."""
    return collect_stablehlo(lowered)["donation"]


# ---------------------------------------------------------------------------
# expected schedule (groups / pairs are in linearized mesh-device order)
# ---------------------------------------------------------------------------


def _mesh_dims(mesh) -> tuple:
    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    shape = dict(mesh.shape)
    W = shape[GRAPH_AXIS]
    R = max(1, math.prod(s for a, s in shape.items() if a != GRAPH_AXIS))
    return R, W


def _graph_groups(R: int, W: int) -> list:
    return [[r * W + g for g in range(W)] for r in range(R)]


def _permute_pair_sets(R: int, W: int, deltas) -> dict:
    """frozenset of (src, tgt) pairs -> "d{delta}{fwd|rev}" label for every
    live delta in both put directions — a traced permute must match one."""
    sets = {}
    for d in deltas:
        for sign, tag in ((1, "fwd"), (-1, "rev")):
            pairs = frozenset(
                (r * W + i, r * W + ((i + sign * d) % W))
                for r in range(R)
                for i in range(W)
            )
            sets[pairs] = f"d{d}:{tag}"
    return sets


def _sched_pair_sets(R: int, W: int, schedule) -> dict:
    """frozenset of (src, tgt) device pairs -> "r{round}:{fwd|rev}" for
    every compiled round, in both directions (the reverse leg replays the
    schedule with every pair flipped) — a lowered permute under
    ``halo_impl='sched'`` must match one round exactly. Unlike the
    delta-ring sets these are PARTIAL: a round names only its members,
    and the non-members' zero-fill is absorbed by the executor's scratch
    rows, so a full ring here would be drift, not correctness."""
    sets = {}
    for k, rnd in enumerate(schedule.rounds):
        for flip, tag in ((False, "fwd"), (True, "rev")):
            base = [(d, s) for (s, d) in rnd.pairs] if flip else rnd.pairs
            pairs = frozenset(
                (r * W + s, r * W + d) for r in range(R) for (s, d) in base
            )
            sets.setdefault(pairs, f"r{k}:{tag}")
    return sets


def _audit_one_lowering(
    label: str,
    impl: str,
    lowered,
    plan,
    mesh,
    failures: list,
    coll: Optional[dict] = None,
) -> dict:
    """Verify one program's lowered module against the planned schedule;
    returns the program record (and appends failures). Pass a
    pre-collected ``coll`` to share one module walk with the donation
    check."""
    coll = collect_stablehlo(lowered) if coll is None else coll
    R, W = _mesh_dims(mesh)
    deltas = tuple(plan.halo_deltas)
    n_deltas = len(deltas)
    S = plan.halo.s_pad
    groups = _graph_groups(R, W)
    schedule = getattr(plan, "halo_schedule", None)
    pair_sets = (
        _sched_pair_sets(R, W, schedule)
        if impl == "sched" and schedule is not None
        else _permute_pair_sets(R, W, deltas)
    )

    def fail(msg):
        failures.append(f"[hlo:{label}/{impl}] {msg}")

    # split the p2p interpret-mode DMA artifacts out of the all_gather
    # census BY SHAPE (a [.., S, F]-shaped float payload per remote put,
    # plus two tiny integer indices); byte pricing is checked separately
    # below, so a tile whose bytes drifted is reported as a BYTE mismatch,
    # not misdiagnosed as an unscheduled collective. Every other gather is
    # unscheduled.
    tile_gathers, int_gathers, rogue_gathers = [], [], []
    for rec in coll["all_gather"]:
        if impl == "pallas_p2p":
            if (
                # uint8: the fp8 wire payload — shape (not dtype) is what
                # identifies the [.., S, F_wire] send tile either way
                rec["dtype"] in ("float32", "bfloat16", "float16", "uint8")
                and len(rec["shape"]) >= 2
                and rec["shape"][-2] == S
            ):
                tile_gathers.append(rec)
                continue
            if (
                rec["dtype"].startswith(("int", "uint"))
                and rec["bytes"] <= _DMA_ARTIFACT_INT_GATHER_MAX_BYTES
            ):
                int_gathers.append(rec)
                continue
        rogue_gathers.append(rec)

    # no XLA-materialized collective the plan didn't schedule — the class
    # the relaxed rep checker can no longer catch at trace level
    for rec in rogue_gathers:
        fail(
            f"unscheduled all_gather of {rec['shape']} ({rec['dtype']}, "
            f"{rec['bytes']} B) in the lowered module — XLA materialized a "
            f"collective the plan never scheduled (wrong out-spec under "
            f"the relaxed replication checker?)"
        )
    for kind in ("reduce_scatter", "collective_broadcast"):
        for rec in coll[kind]:
            fail(
                f"unscheduled {kind} of {rec['shape']} ({rec['dtype']}) "
                f"in the lowered module"
            )

    # exactly one transport family per lowered program
    n_a2a = len(coll["all_to_all"])
    n_cp = len(coll["collective_permute"])
    n_tile = len(tile_gathers)
    families = [
        name for name, count in (
            ("all_to_all", n_a2a), ("ppermute", n_cp), ("pallas_p2p", n_tile),
        ) if count
    ]
    want_family = impl if impl in ("all_to_all", "pallas_p2p") else "ppermute"
    if len(families) > 1:
        fail(
            "mixed transport families in ONE lowered program: "
            + " + ".join(families)
        )
    for fam, count in (
        ("all_to_all", n_a2a), ("ppermute", n_cp), ("pallas_p2p", n_tile),
    ):
        if fam != want_family and count:
            fail(
                f"pinned lowering {impl!r} but the module contains {count} "
                f"{fam} op(s)"
            )
    if not {
        "all_to_all": n_a2a, "ppermute": n_cp, "pallas_p2p": n_tile,
    }[want_family]:
        fail(f"pinned lowering {impl!r} lowered no {want_family} ops at all")

    # per-operand bytes == obs.footprint's pricing at the LOWERED
    # width/dtype, and groups/pairs == the planned schedule
    operand_rows = []
    for rec in coll["all_to_all"]:
        F = rec["shape"][-1] if rec["shape"] else 0
        want = _expected_bytes(plan, rec["dtype"], F)["a2a_operand_bytes"]
        operand_rows.append({**{k: rec[k] for k in ("op", "shape", "dtype", "bytes")},
                             "footprint_bytes": want})
        if rec["bytes"] != want:
            fail(
                f"all_to_all operand {rec['shape']} ({rec['dtype']}) is "
                f"{rec['bytes']} B lowered; footprint prices {want} B"
            )
        if rec["replica_groups"] != groups:
            fail(
                f"all_to_all replica_groups {rec['replica_groups']} != "
                f"planned graph-axis groups {groups}"
            )
    for rec in coll["collective_permute"]:
        F = rec["shape"][-1] if rec["shape"] else 0
        exp = _expected_bytes(plan, rec["dtype"], F)
        if impl == "sched":
            # per-round membership (rounds differ in height); the full
            # multiset — every priced round exactly legs times — is
            # pinned cross-program in audit_workload_hlo
            allowed = set(exp["sched_round_bytes"])
            member = rec["bytes"] in allowed
            operand_rows.append({
                **{k: rec[k] for k in ("op", "shape", "dtype", "bytes")},
                "footprint_bytes": rec["bytes"] if member else 0,
            })
            if not member:
                fail(
                    f"collective_permute operand {rec['shape']} "
                    f"({rec['dtype']}) is {rec['bytes']} B lowered; "
                    f"footprint prices rounds of {sorted(allowed)} B"
                )
        else:
            want = exp["ppermute_round_bytes"]
            operand_rows.append({
                **{k: rec[k] for k in ("op", "shape", "dtype", "bytes")},
                "footprint_bytes": want,
            })
            if rec["bytes"] != want:
                fail(
                    f"collective_permute operand {rec['shape']} "
                    f"({rec['dtype']}) is {rec['bytes']} B lowered; "
                    f"footprint prices {want} B per round"
                )
        pairs = frozenset(map(tuple, rec["source_target_pairs"] or []))
        if pairs not in pair_sets:
            fail(
                f"collective_permute pairs {sorted(pairs)} match no "
                + (
                    f"compiled schedule round (id="
                    f"{schedule.schedule_id}, W={W})"
                    if impl == "sched" and schedule is not None
                    else f"live delta ring of the plan "
                         f"(deltas={deltas}, W={W})"
                )
            )
    for rec in tile_gathers:
        F = rec["shape"][-1] if rec["shape"] else 0
        want = _expected_bytes(plan, rec["dtype"], F)["ppermute_round_bytes"]
        operand_rows.append({**{k: rec[k] for k in ("op", "shape", "dtype", "bytes")},
                             "footprint_bytes": want})
        if rec["bytes"] != want:
            fail(
                f"p2p tile-payload gather {rec['shape']} ({rec['dtype']}) "
                f"is {rec['bytes']} B lowered; footprint prices {want} B "
                f"per put"
            )
        if rec["replica_groups"] is not None and rec["replica_groups"] != groups:
            fail(
                f"p2p DMA-artifact gather groups {rec['replica_groups']} != "
                f"planned graph-axis groups {groups}"
            )
    if impl == "pallas_p2p" and n_tile:
        want_ints = _DMA_ARTIFACT_INT_GATHERS_PER_PUT * n_tile
        if len(int_gathers) != want_ints:
            fail(
                f"{len(int_gathers)} scalar index gathers for {n_tile} "
                f"remote put(s); the interpret DMA discharge emits exactly "
                f"{_DMA_ARTIFACT_INT_GATHERS_PER_PUT} per put"
            )

    # fp32 accumulation at the artifact level: reductions never run
    # sub-32-bit (bf16 may ride the wire; all_reduce must not)
    narrow = [
        r for r in coll["all_reduce"]
        if r["dtype"] in ("bfloat16", "float16")
    ]
    if narrow:
        fail(
            f"all_reduce on a sub-32-bit dtype in the lowered module: "
            f"{[(r['shape'], r['dtype']) for r in narrow[:4]]}"
        )

    return {
        "program": label,
        "impl": impl,
        "num_all_to_all": n_a2a,
        "num_collective_permute": n_cp,
        "num_tile_gathers": n_tile,
        "num_index_gathers": len(int_gathers),
        "num_all_reduce": len(coll["all_reduce"]),
        "collective_operands": operand_rows,
        "s_pad": int(S),
        "num_halo_deltas": n_deltas,
    }


def _donation_failures(don: dict, expected_donors: int, label: str,
                       failures: list) -> dict:
    """Donation must survive lowering: donor-entry count == donated
    leaves, and every donor argument's (shape, dtype) covered by an
    output — otherwise XLA drops the alias at compile time and peak HBM
    grows by the donated footprint. ``don`` is the donation slice of an
    already-collected module walk (:func:`donation_entries` /
    ``collect_stablehlo(...)["donation"]``) — callers that walked the
    module once don't pay a second recursive pass."""
    from collections import Counter

    declared = don["alias_args"] + len(don["donor_args"])
    rec = {
        "expected_donors": int(expected_donors),
        "donor_args": declared,
        "alias_args": don["alias_args"],
        "uncovered": [],
    }
    if declared != expected_donors:
        failures.append(
            f"[hlo:{label}] {declared} donation entrie(s) survived lowering;"
            f" {expected_donors} leaves were donated — donation dropped "
            f"before XLA ever saw it"
        )
    produced = Counter(don["result_types"])
    for t in don["donor_args"]:
        if produced.get(t, 0) > 0:
            produced[t] -= 1
        else:
            rec["uncovered"].append({"shape": list(t[0]), "elt": t[1]})
    if rec["uncovered"]:
        failures.append(
            f"[hlo:{label}] donated argument type(s) with no matching "
            f"output in the lowered module (XLA will drop the alias): "
            f"{rec['uncovered'][:4]}"
        )
    return rec


def _jit_cache_entries(fn) -> Optional[int]:
    """The jitted program's executable-cache size — MUST stay 0 across
    this tier (lower-only; a ``.compile()`` sneaking in shows up here and
    turns the audit red). Returns None when the probe itself is
    unavailable (jax moved the private ``_cache_size``) — the caller
    treats that as a FAILURE, not a pass: a contract that silently stops
    being checked is worse than one that loudly asks for an update."""
    cache_size = getattr(fn, "_cache_size", None)
    if not callable(cache_size):
        return None
    try:
        return int(cache_size())
    except Exception:
        return None


def audit_workload_hlo(
    w,
    impls=HALO_IMPLS,
    programs=None,
) -> dict:
    """Lower every (program, halo lowering) pair and verify the full
    post-lowering contract; returns a ``kind="hlo_audit"`` report dict
    (same caller contract as :func:`~dgraph_tpu.analysis.trace.
    audit_workload`: ``ok`` + ``failures``, the caller decides whether to
    raise)."""
    import jax

    from dgraph_tpu import config as _cfg

    failures: list = []
    program_records = []
    legs: dict = {}
    donation = None
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl, _cfg.use_pallas_p2p)
    audited_impls = [
        impl for impl in impls
        if impl != "sched"
        or getattr(w.plan_np, "halo_schedule", None) is not None
    ]
    try:
        for impl in audited_impls:
            _cfg.set_flags(halo_impl=impl, tuned_halo_impl=None)
            _cfg.set_flags(
                use_pallas_p2p=True if impl == "pallas_p2p" else saved[2]
            )
            for label, build in (programs or PROGRAMS).items():
                fn, args = build(w)
                lowered = lower_program(fn, args)
                coll = collect_stablehlo(lowered)
                rec = _audit_one_lowering(
                    label, impl, lowered, w.plan_np, w.mesh, failures,
                    coll=coll,
                )
                rec["jit_cache_entries"] = _jit_cache_entries(fn)
                if rec["jit_cache_entries"] is None:
                    failures.append(
                        f"[hlo:{label}/{impl}] jit-cache probe unavailable "
                        f"(jax moved _cache_size?) — the lower-only "
                        f"contract is unenforceable; update analysis.hlo "
                        f"for this jax version"
                    )
                elif rec["jit_cache_entries"]:
                    failures.append(
                        f"[hlo:{label}/{impl}] jit cache holds "
                        f"{rec['jit_cache_entries']} executable(s) after a "
                        f"lower-only audit — something compiled"
                    )
                program_records.append(rec)
                if impl == "all_to_all":
                    legs[label] = rec["num_all_to_all"]
                    if label == "train_step":
                        donated = len(jax.tree.leaves((w.params, w.opt_state)))
                        donation = _donation_failures(
                            coll["donation"], donated, f"{label}/{impl}",
                            failures,
                        )
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            use_pallas_p2p=saved[2],
        )

    # cross-lowering count pins, mirrored from the trace tier but against
    # the LOWERED ops: legs measured from the all_to_all-pinned module
    n_deltas = len(w.plan_np.halo_deltas)
    for rec in program_records:
        if rec["impl"] == "all_to_all" or rec["program"] not in legs:
            continue
        want = legs[rec["program"]] * n_deltas
        if rec["impl"] == "sched":
            schedule = w.plan_np.halo_schedule
            n_rounds = schedule.num_rounds
            want = legs[rec["program"]] * n_rounds
            if rec["num_collective_permute"] != want:
                failures.append(
                    f"[hlo:{rec['program']}/{rec['impl']}] "
                    f"{rec['num_collective_permute']} collective_permutes "
                    f"lowered; expected legs({legs[rec['program']]}) * "
                    f"schedule rounds({n_rounds}) = {want}"
                )
                continue
            groups: dict = {}
            for o in rec["collective_operands"]:
                F = o["shape"][-1] if o["shape"] else 0
                groups.setdefault((o["dtype"], F), []).append(o["bytes"])
            for (dt, F), lowered_b in sorted(groups.items()):
                exp = _expected_bytes(
                    w.plan_np, dt, F
                )["sched_round_bytes"]
                k, r = divmod(len(lowered_b), max(len(exp), 1))
                if not exp or r or sorted(lowered_b) != sorted(exp * k):
                    failures.append(
                        f"[hlo:{rec['program']}/{rec['impl']}] lowered "
                        f"round bytes at ({dt}, F={F}) "
                        f"{sorted(lowered_b)[:8]} != footprint rounds "
                        f"{sorted(exp)[:8]} x {k} leg(s)"
                    )
        elif rec["impl"] in ("ppermute", "overlap"):
            if rec["num_collective_permute"] != want:
                failures.append(
                    f"[hlo:{rec['program']}/{rec['impl']}] "
                    f"{rec['num_collective_permute']} collective_permutes "
                    f"lowered; expected legs({legs[rec['program']]}) * "
                    f"num_halo_deltas({n_deltas}) = {want}"
                )
        elif rec["impl"] == "pallas_p2p":
            if rec["num_tile_gathers"] != want:
                failures.append(
                    f"[hlo:{rec['program']}/{rec['impl']}] "
                    f"{rec['num_tile_gathers']} tile-payload DMA artifacts "
                    f"lowered; expected one per remote put = "
                    f"legs({legs[rec['program']]}) * num_halo_deltas"
                    f"({n_deltas}) = {want}"
                )

    return {
        "kind": "hlo_audit",
        "world_size": w.world_size,
        "num_nodes": w.num_nodes,
        "num_halo_deltas": n_deltas,
        "impls": list(audited_impls),
        "exchange_legs": legs,
        "programs": program_records,
        "donation": donation,
        "failures": failures,
        "ok": not failures,
    }


def hlo_drift_record(
    world_size: int = 8, *, num_nodes: int = 4096, num_edges: int = 16384,
    feat_dim: int = 32, seed: int = 0,
) -> dict:
    """Compact lowered-schedule comparison for bench's no-healthy-chip
    fallback (ROADMAP item 5, third non-null tier beside
    ``schedule_drift`` and ``cpu_scan_delta``): the TRAIN step only, one
    row per halo lowering with lowered-vs-footprint bytes plus the
    donation census, so a wedged round still lands a non-null signal
    about the artifact XLA would have compiled."""
    from dgraph_tpu.analysis.trace import _train_program

    w = build_audit_workload(
        world_size, num_nodes=num_nodes, num_edges=num_edges,
        feat_dim=feat_dim, seed=seed,
    )
    report = audit_workload_hlo(w, programs={"train_step": _train_program})
    per_impl = {}
    for rec in report["programs"]:
        ops = rec["collective_operands"]
        per_impl[rec["impl"]] = {
            "collective_count": len(ops),
            "lowered_bytes": sum(o["bytes"] for o in ops),
            "footprint_bytes": sum(o["footprint_bytes"] for o in ops),
        }
    return {
        "kind": "hlo_drift",
        "workload": {
            "world_size": world_size, "nodes": num_nodes, "edges": num_edges,
            "feat_dim": feat_dim, "seed": seed,
        },
        "num_halo_deltas": report["num_halo_deltas"],
        "train_step_by_impl": per_impl,
        "donation": report["donation"],
        "failures": report["failures"],
        "drift": not report["ok"],
    }
