"""``python -m dgraph_tpu.analysis.host`` — the host-side concurrency &
durability auditor standalone CLI.

Default mode audits the clean tree (per-file rules pragma-aware through
the lint machinery, plus the repo-level lock-order and chaos-coverage
checks) and exits nonzero on any finding; ``--selftest true`` runs the
per-rule fixture pairs and the vacuity mutants — unlocked guarded-field
write, seeded lock-order cycle, bare-open manifest write,
pointer-flip-before-payload, unregistered chaos fire site — each of
which must go RED, then the clean-tree audit.  The whole tier is
stdlib-``ast`` (lint's ``jax-free-module`` rule covers
``dgraph_tpu/analysis/host/``): it traces nothing, lowers nothing, and
performs zero XLA compiles by construction.  Every exit path carries a
RunHealth record.
"""

from __future__ import annotations

import dataclasses
import json

from dgraph_tpu.analysis.host import host_selftest_failures, run_host_audit


@dataclasses.dataclass
class Config:
    """Host-side concurrency & durability auditor (``--selftest`` runs
    the fixture pairs + vacuity mutants + clean-tree audit; default mode
    audits the tree and exits nonzero on any finding)."""

    selftest: bool = False
    root: str = ""  # "" = the repo containing this package
    indent: int = 0


def main(cfg: Config) -> dict:
    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("analysis.host.cli")
    try:
        if cfg.selftest:
            failures = host_selftest_failures(cfg.root or None)
            out = {"kind": "host_selftest", "failures": failures}
        else:
            out = run_host_audit(cfg.root or None)
            failures = out["failures"]
        out["run_health"] = health.finish(
            "; ".join(failures) if failures else None,
            wedge="stage_failure" if failures else None,
        )
        print(json.dumps(out, indent=cfg.indent or None))
        if failures:
            raise SystemExit(
                "host audit FAILED: " + "; ".join(failures[:10])
            )
        return out
    except SystemExit:
        raise
    except BaseException as e:  # every exit path carries a RunHealth record
        print(json.dumps({
            "kind": "host_audit",
            "failures": [f"crashed: {type(e).__name__}: {e}"],
            "run_health": health.finish(
                f"host audit crashed: {type(e).__name__}: {e}",
                wedge="interrupted"
                if isinstance(e, KeyboardInterrupt) else "stage_failure",
            ),
        }))
        raise


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
