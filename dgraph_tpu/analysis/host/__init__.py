"""Host-side concurrency & durability auditor: race / deadlock / torn-write
rules over the jax-free control plane.

The four device-program tiers (:mod:`~dgraph_tpu.analysis.trace`,
:mod:`~dgraph_tpu.analysis.hlo`, :mod:`~dgraph_tpu.analysis.kernel`,
:mod:`~dgraph_tpu.analysis.spmd`) prove what XLA runs; crash safety and
liveness now hinge equally on the *host-side* concurrent control plane —
the serve engine/batcher/registry/tenancy/deltas stack, membership's
heartbeat daemons, the shrink replan thread, and the fsync+rename
generation-pointer protocols (``world.json`` / ``serving.json`` /
plan-shard manifests).  Those invariants were enforced only by dynamic
chaos tests, which sample schedules; every rule below is *static* — the
lock that guards a field, the acquisition order of two locks, and the
statement that flips a generation pointer are all visible in the AST — so
the whole tier runs pure-stdlib ``ast`` analysis: this package is itself
a ``jax-free-module`` lint target, it traces nothing and lowers nothing,
and it performs zero XLA compiles by construction (the only tier whose
compile-freedom needs no jit-cache counter to prove).

Rule families (all registered in :data:`dgraph_tpu.analysis.lint.RULES`,
so ``--list_rules``, the docs catalog pin, and the ``# lint:
allow(<rule>)`` pragma all work unchanged):

- ``host-lock-discipline`` — per class, infer the *guarded-field set*
  (attributes ever written inside a ``with self._lock`` /
  ``with self._cv`` block, where the lock attribute was assigned a
  ``threading.Lock/RLock/Condition``; container mutations like
  ``self._q.append`` count as writes) and flag any read or write of a
  guarded field outside that lock — including from nested functions
  handed to ``threading.Thread`` and daemon loops (entering a nested
  function RESETS the held-lock context: its execution time is unknown,
  so a lexically-enclosing ``with`` proves nothing).  Private helpers
  whose every in-class call site holds the lock are treated as lock-held
  (the ``TenantTable._state`` pattern); ``__init__`` is exempt (the
  object is not shared yet).
- ``host-lock-order`` — build the lock-acquisition-order graph (lock
  held -> lock acquired, following direct calls transitively: ``self.m``
  to the same class, bare names to the same module, unambiguous
  attribute calls across the scanned set) over every control-plane
  module at once, including module-level locks like ``chaos._LOCK``, and
  fail on any cycle.  On real transports an inverted acquisition order
  *deadlocks* — it never errors — which is exactly why no dynamic test
  reports it.
- ``host-durable-write`` — every write destined for a durable artifact
  (``world.json`` / ``serving.json`` pointers, ``graph_g<N>.npz``
  snapshots, plan-shard manifests, tuning records) must flow through
  the blessed fsync+rename writers (:func:`~dgraph_tpu.plan_shards.
  atomic_write_json`, :func:`~dgraph_tpu.train.checkpoint.
  atomic_pickle_dump`, :func:`~dgraph_tpu.plan_shards.atomic_savez`).
  A bare ``open(path, "w")`` or a direct ``np.savez`` to such a path is
  RED: without the fsync, ``os.replace`` can commit the *name* before
  the kernel commits the *bytes*, and a host crash leaves a torn
  artifact under a valid name (the PR 5 torn-rename class).  Tainting is
  local dataflow: a name assigned from ``world_path(...)`` stays
  durable through ``tmp = path + ".tmp"``.
- ``host-pointer-flip-last`` — in any function that writes a generation
  pointer (``write_world`` or ``atomic_write_json`` of a
  ``world_path``-derived path), the pointer write must be the LAST
  filesystem effect on every intra-procedural CFG path to the exit: the
  old-or-new-never-torn contract holds only if every payload artifact
  is durable *before* the flip.  The walker understands early returns
  (``replan``'s flip-then-return inside a retry loop is GREEN), loop
  back edges, and ``try/finally``.
- ``host-chaos-coverage`` — bidirectional drift check between
  ``chaos.KNOWN_POINTS`` and the tree's ``chaos.fire("<point>")`` call
  sites: every registered point must have a fire site outside
  ``dgraph_tpu/chaos/`` (a point only its own selftest fires is
  documentation, not coverage), and every fire site must name a
  registered point (a typo'd point is silently inert — the exact
  failure mode the parse-time grammar guard exists to prevent,
  re-opened one layer up).

``python -m dgraph_tpu.analysis.host`` audits the clean tree (nonzero
exit on any finding); ``--selftest true`` runs the per-rule fixture
pairs plus the vacuity mutants (unlocked guarded-field write, seeded
lock-order cycle, bare-open manifest write, pointer-flip-before-payload,
unregistered chaos fire site — each must go RED), then the clean-tree
audit, and asserts jax was never imported.  The per-file rules also run
in every ``analysis.lint`` pass (``python -m dgraph_tpu.analysis``,
``scripts/check.py``); the repo-level graph rules run through
:func:`run_host_audit`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Optional

from dgraph_tpu.analysis.lint import (
    Finding,
    _dotted,
    _last_segment,
    iter_source_files,
    lint_file,
    path_matcher,
    repo_root,
    rule,
)

__all__ = [
    "HOST_SCOPE",
    "scan_module",
    "class_concurrency_findings",
    "build_lock_graph",
    "lock_order_findings",
    "durable_write_findings",
    "pointer_flip_findings",
    "chaos_coverage_findings",
    "run_host_audit",
    "host_selftest_failures",
]

# the jax-free control-plane modules this tier audits (repo-relative
# posix prefixes) — the thread/lock/daemon surface grown by the serving
# control plane, elastic membership, and the shrink/replan machinery
HOST_SCOPE = (
    "dgraph_tpu/serve/",
    "dgraph_tpu/comm/membership.py",
    "dgraph_tpu/train/supervise.py",
    "dgraph_tpu/train/shrink.py",
    "dgraph_tpu/train/grow.py",
    "dgraph_tpu/train/elastic.py",
    "dgraph_tpu/plan_shards.py",
    "dgraph_tpu/chaos/",
    "dgraph_tpu/obs/spans.py",
)

# the durable-artifact writers additionally cover the checkpoint and
# tuning-record modules: their artifacts are exactly the "durable" set
# (ckpt steps, tune_<sig>.json) the atomic-write contract names
DURABLE_SCOPE = HOST_SCOPE + (
    "dgraph_tpu/train/checkpoint.py",
    "dgraph_tpu/tune/record.py",
    # the perf-trajectory ledger: an append-only store that must survive
    # host crashes mid-append (torn trailing lines are tolerated by its
    # reader, but the append itself must flush+fsync)
    "dgraph_tpu/obs/ledger.py",
)

LOCK_CONSTRUCTORS = frozenset({"Lock", "RLock", "Condition"})

# method calls that mutate a container in place — a `self._q.append(x)`
# is a WRITE to `_q` for guarded-field inference
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend", "insert",
    "pop", "popleft", "popitem", "remove", "setdefault", "sort", "update",
})

# attribute-call names too generic to resolve across classes for the
# lock graph (file handles, futures, dict/list methods, lock protocol)
ATTR_RESOLUTION_BLOCKLIST = frozenset({
    "write", "read", "close", "flush", "open", "get", "set", "put", "pop",
    "append", "add", "update", "join", "start", "stop", "wait", "notify",
    "notify_all", "acquire", "release", "end", "items", "keys", "values",
    "copy", "clear", "result", "cancel", "save", "load", "run", "format",
    "strip", "split", "sleep",
})

# blessed durable writers (tmp + flush + fsync + os.replace inside; the
# ledger's append variant flush+fsyncs the appended line instead — its
# reader skips a torn trailing line with a reason, so append is durable)
ATOMIC_WRITERS = frozenset({
    "atomic_write_json", "atomic_pickle_dump", "atomic_savez",
    "atomic_append_jsonl",
})

# path-returning helpers whose results name durable artifacts
DURABLE_PATH_FNS = frozenset({
    "world_path", "graph_path", "manifest_path", "record_path",
    "ledger_path",
})
DURABLE_NAME_HINTS = ("world.json", "serving.json", "manifest.json",
                      "ledger.jsonl")

# calls that touch the filesystem, for the pointer-flip-last walk
FS_EFFECT_CALLS = frozenset({
    "replace", "rename", "link", "unlink", "remove", "rmdir", "makedirs",
    "mkdir", "savez", "savez_compressed", "dump", "write_manifest",
    "save_checkpoint", "build_plan_shards", "write_world",
}) | ATOMIC_WRITERS

POINTER_WRITE_CALLS = frozenset({"write_world"})


def _self_attr(node) -> Optional[str]:
    """Attribute name when ``node`` is ``self.<attr>``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_root_attr(node) -> Optional[str]:
    """The first attribute above ``self`` in a chain like
    ``self._q.append`` or ``self._entries[name]`` — the field a mutator
    call / subscript store actually mutates."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        inner = node.value
        got = _self_attr(inner)
        if got is not None:
            return got
        node = inner
    return None


def _is_lock_ctor(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    if _last_segment(value.func) not in LOCK_CONSTRUCTORS:
        return False
    dotted = _dotted(value.func)
    return dotted.startswith("threading.") or "." not in dotted


# ---------------------------------------------------------------------------
# the module scanner (shared by lock-discipline and lock-order)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FnScan:
    """Concurrency-relevant facts about one function/method body."""

    relpath: str
    cls: Optional[str]
    name: str
    line: int
    # [(lock_id, line, held_before: tuple)] for every `with <lock>` entry
    acquires: list = dataclasses.field(default_factory=list)
    # [(held: tuple, kind, target, line)] for every call; kind is
    # "self" | "bare" | "attr"
    calls: list = dataclasses.field(default_factory=list)
    # [(field, "read"|"write", line, held_attrs: tuple)] self-attr access
    accesses: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassScan:
    name: str
    lock_attrs: frozenset
    methods: dict  # name -> FnScan


@dataclasses.dataclass
class ModuleScan:
    relpath: str
    module_locks: dict  # name -> line, for NAME = threading.Lock() at top
    classes: dict       # name -> ClassScan
    functions: dict     # name -> FnScan (module level)


def _class_lock_attrs(cls_node: ast.ClassDef) -> frozenset:
    attrs = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                a = _self_attr(t)
                if a:
                    attrs.add(a)
    return frozenset(attrs)


def _lock_id_of(expr, relpath, cls_name, lock_attrs, module_locks):
    """The lock identity a ``with`` context expression acquires, or None
    when it is not a lock (``with open(...)``, ``with spans.span(...)``)."""
    attr = _self_attr(expr)
    if attr is not None and attr in lock_attrs:
        return ("class", relpath, cls_name, attr)
    if isinstance(expr, ast.Name) and expr.id in module_locks:
        return ("module", relpath, expr.id)
    if isinstance(expr, ast.Call):
        fname = _last_segment(expr.func)
        if "lock" in fname.lower():
            return ("factory", relpath, fname)
    return None


def _scan_fn(
    fn_node, relpath, cls_name, lock_attrs, module_locks
) -> FnScan:
    scan = FnScan(relpath, cls_name, fn_node.name, fn_node.lineno)

    def held_attrs(held) -> tuple:
        return tuple(
            lid[3] for lid in held if lid[0] == "class" and lid[2] == cls_name
        )

    def visit(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # thread escape: a nested function's execution time is
            # unknown (Thread targets, callbacks) — an enclosing `with`
            # proves nothing about when its body runs
            body = node.body if not isinstance(node, ast.Lambda) else [
                ast.Expr(node.body)
            ]
            for child in body:
                visit(child, ())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            newly = []
            for item in node.items:
                visit(item.context_expr, held)
                lid = _lock_id_of(
                    item.context_expr, relpath, cls_name, lock_attrs,
                    module_locks,
                )
                if lid is not None:
                    scan.acquires.append((lid, node.lineno, tuple(held)))
                    newly.append(lid)
            inner = tuple(held) + tuple(newly)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Call):
            fname = _last_segment(node.func)
            if isinstance(node.func, ast.Attribute):
                # `self.m()` is a same-class method call; `self.field.m()`
                # is a call INTO the object held in `field` (attr kind)
                if (
                    isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                ):
                    kind = "self"
                else:
                    kind = "attr"
                target = node.func.attr
                # container mutation on a self field is a write
                if node.func.attr in MUTATOR_METHODS:
                    root = _self_root_attr(node.func)
                    if root is not None and root not in lock_attrs:
                        scan.accesses.append(
                            (root, "write", node.lineno, held_attrs(held))
                        )
            elif isinstance(node.func, ast.Name):
                kind, target = "bare", node.func.id
            else:
                kind, target = "attr", fname
            if target:
                scan.calls.append((tuple(held), kind, target, node.lineno))
            for child in ast.iter_child_nodes(node):
                visit(child, held)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                targets = t.elts if isinstance(t, ast.Tuple) else [t]
                for tt in targets:
                    if isinstance(tt, ast.Subscript):
                        root = _self_root_attr(tt)
                        if root is not None and root not in lock_attrs:
                            scan.accesses.append(
                                (root, "write", node.lineno,
                                 held_attrs(held))
                            )
        if isinstance(node, ast.AugAssign):
            a = _self_attr(node.target)
            if a is not None and a not in lock_attrs:
                scan.accesses.append(
                    (a, "write", node.lineno, held_attrs(held))
                )
        if isinstance(node, ast.Attribute):
            a = _self_attr(node)
            if a is not None and a not in lock_attrs:
                kind = (
                    "write"
                    if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read"
                )
                scan.accesses.append((a, kind, node.lineno,
                                      held_attrs(held)))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn_node.body:
        visit(stmt, ())
    return scan


def scan_module(relpath: str, tree: ast.AST) -> ModuleScan:
    """Full concurrency scan of one module: module-level locks, classes
    with their lock attributes and per-method :class:`FnScan`, and
    module-level functions."""
    module_locks = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    module_locks[t.id] = node.lineno
    classes, functions = {}, {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.ClassDef):
            lock_attrs = _class_lock_attrs(node)
            methods = {
                m.name: _scan_fn(m, relpath, node.name, lock_attrs,
                                 module_locks)
                for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            classes[node.name] = ClassScan(node.name, lock_attrs, methods)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[node.name] = _scan_fn(
                node, relpath, None, frozenset(), module_locks
            )
    return ModuleScan(relpath, module_locks, classes, functions)


# ---------------------------------------------------------------------------
# rule 1: host-lock-discipline
# ---------------------------------------------------------------------------


def _held_extras(cs: ClassScan) -> tuple:
    """``(blessed, evidence)`` per method, for private helpers only (a
    public method can be entered from outside the class, where no call
    site is visible to this analysis):

    - ``blessed[m]`` — lock attrs EVERY in-class ``self.m()`` call site
      holds (intersection; fixpoint through calling helpers).  A body
      may *assume* these held, so its accesses are safe.
    - ``evidence[m]`` — lock attrs held at ANY in-class call site
      (union).  A write in ``m`` under such evidence marks the field
      lock-guarded for inference — so a helper called both with and
      without the lock still declares the contract its locked callers
      imply, and its own unlocked call sites then go RED.
    """
    blessed = {m: frozenset() for m in cs.methods}
    evidence = {m: frozenset() for m in cs.methods}
    sites: dict = {m: [] for m in cs.methods}
    for caller, scan in cs.methods.items():
        for held, kind, target, _line in scan.calls:
            if kind == "self" and target in cs.methods:
                sites[target].append((caller, held))

    # calls carry full held lock-id tuples; reduce to this class's attrs
    def attrs_of(held):
        return frozenset(
            lid[3] for lid in held
            if lid[0] == "class" and lid[2] == cs.name
        )

    for _ in range(len(cs.methods) + 1):
        changed = False
        for m, callers in sites.items():
            if not callers or not m.startswith("_") or m.startswith("__"):
                continue
            agreed, seen = None, frozenset()
            for caller, held in callers:
                eff = attrs_of(held) | blessed.get(caller, frozenset())
                agreed = eff if agreed is None else (agreed & eff)
                seen |= eff | evidence.get(caller, frozenset())
            agreed = agreed or frozenset()
            if agreed != blessed[m] or seen != evidence[m]:
                blessed[m], evidence[m] = agreed, seen
                changed = True
        if not changed:
            break
    return blessed, evidence


def class_concurrency_findings(relpath: str, tree: ast.AST,
                               lines: Optional[list] = None) -> list:
    """host-lock-discipline over one module: guarded-field inference +
    out-of-lock access flagging, per class."""
    ms = scan_module(relpath, tree)
    findings = []
    for cs in ms.classes.values():
        if not cs.lock_attrs:
            continue
        blessed, evidence = _held_extras(cs)
        # guarded inference: fields written with a class lock held —
        # lexically, or inside a private helper at least one of whose
        # call sites holds the lock (the contract its callers imply)
        guarded: dict = {}
        write_line: dict = {}
        for mname, scan in cs.methods.items():
            if mname == "__init__":
                continue
            infer_extra = evidence.get(mname, frozenset())
            for field, kind, line, held in scan.accesses:
                if kind != "write":
                    continue
                locks = frozenset(held) | infer_extra
                if locks:
                    guarded.setdefault(field, set()).update(locks)
                    write_line.setdefault(field, line)
        # flagging: any access to a guarded field without its lock held
        # FOR SURE (lexically, or blessed: every call site holds it)
        seen = set()
        for mname, scan in cs.methods.items():
            if mname == "__init__":
                continue
            eff_extra = blessed.get(mname, frozenset())
            for field, kind, line, held in scan.accesses:
                if field not in guarded:
                    continue
                if (frozenset(held) | eff_extra) & guarded[field]:
                    continue
                key = (field, line)
                if key in seen:
                    continue
                seen.add(key)
                locks = "/".join(sorted(guarded[field]))
                findings.append(Finding(
                    "host-lock-discipline", relpath, line,
                    f"{kind} of {cs.name}.{field} outside 'self.{locks}' "
                    f"(guarded: written under the lock at line "
                    f"{write_line[field]}); an unlocked {kind} races the "
                    f"locked writers — take the lock or snapshot under it",
                ))
    findings.sort(key=lambda f: f.line)
    return findings


@rule(
    "host-lock-discipline",
    "per class, any attribute ever written under a 'with self.<lock>' "
    "block (threading.Lock/RLock/Condition) is lock-guarded; every other "
    "read/write of it must hold the same lock — including from "
    "threading.Thread targets and daemon loops (nested functions reset "
    "the held-lock context). __init__ is exempt; private helpers whose "
    "every in-class call site holds the lock count as lock-held",
    path_matcher(*HOST_SCOPE),
    scope="serve/, comm/membership.py, train/{supervise,shrink,elastic}.py"
          ", plan_shards.py, chaos/, obs/spans.py",
)
def check_host_lock_discipline(relpath: str, tree: ast.AST, lines: list):
    return class_concurrency_findings(relpath, tree, lines)


# ---------------------------------------------------------------------------
# rule 2: host-lock-order (repo-level)
# ---------------------------------------------------------------------------


def _render_lock(lid: tuple) -> str:
    if lid[0] == "class":
        return f"{lid[1]}::{lid[2]}.{lid[3]}"
    if lid[0] == "module":
        return f"{lid[1]}::{lid[2]}"
    return f"{lid[1]}::{lid[2]}()"


def build_lock_graph(modules: dict) -> dict:
    """The lock-acquisition-order graph over ``{relpath: ast}``.

    Returns ``{"edges": {(src, dst): (relpath, line)}, "locks": [...]}``
    where an edge src -> dst means "src held while dst is acquired",
    following direct calls transitively (``self.m`` -> same class, bare
    names -> same module, unambiguous attribute calls -> the one scanned
    function/method of that name)."""
    scans = {rp: scan_module(rp, tree) for rp, tree in modules.items()}
    # global indices for call resolution
    by_name: dict = {}
    for ms in scans.values():
        for fs in ms.functions.values():
            by_name.setdefault(fs.name, []).append(fs)
        for cs in ms.classes.values():
            for fs in cs.methods.values():
                by_name.setdefault(fs.name, []).append(fs)

    def resolve(fs: FnScan, kind: str, target: str) -> Optional[FnScan]:
        ms = scans[fs.relpath]
        if kind == "self" and fs.cls:
            return ms.classes[fs.cls].methods.get(target)
        if kind == "bare":
            return ms.functions.get(target)
        if target in ATTR_RESOLUTION_BLOCKLIST:
            return None
        cands = by_name.get(target, [])
        return cands[0] if len(cands) == 1 else None

    memo: dict = {}

    def locks_tx(fs: FnScan, stack: tuple) -> frozenset:
        key = (fs.relpath, fs.cls, fs.name)
        if key in memo:
            return memo[key]
        if key in stack:
            return frozenset()
        out = {lid for lid, _l, _h in fs.acquires}
        for _held, kind, target, _line in fs.calls:
            callee = resolve(fs, kind, target)
            if callee is not None:
                out |= locks_tx(callee, stack + (key,))
        memo[key] = frozenset(out)
        return memo[key]

    edges: dict = {}
    all_scans = [
        fs
        for ms in scans.values()
        for fs in list(ms.functions.values())
        + [m for cs in ms.classes.values() for m in cs.methods.values()]
    ]
    for fs in all_scans:
        for lid, line, held_before in fs.acquires:
            for h in held_before:
                if h != lid:
                    edges.setdefault((h, lid), (fs.relpath, line))
        for held, kind, target, line in fs.calls:
            if not held:
                continue
            callee = resolve(fs, kind, target)
            if callee is None:
                continue
            for m in locks_tx(callee, ()):
                for h in held:
                    if h != m:
                        edges.setdefault((h, m), (fs.relpath, line))
    locks = sorted({lid for e in edges for lid in e})
    # the per-module scans ride along so callers (run_host_audit's
    # guarded-class summary) never re-parse or re-scan the same sources
    return {"edges": edges, "locks": locks, "scans": scans}


def _find_cycles(edges: dict) -> list:
    """One representative cycle per strongly connected component of the
    edge set (Tarjan).  SCC-based on purpose: ANY cycle — any length,
    any node ordering — makes its SCC non-trivial, so no deadlockable
    order can hide (a path-enumeration shortcut here once missed
    non-monotone 3-cycles; pinned in tests/test_analysis_host.py)."""
    adj: dict = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    nodes = sorted({n for e in edges for n in e})
    index: dict = {}
    low: dict = {}
    onstack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strong(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in onstack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                onstack.discard(w)
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for n in nodes:
        if n not in index:
            strong(n)

    cycles = []
    for comp in sccs:
        compset = set(comp)
        if len(comp) == 1 and comp[0] not in adj.get(comp[0], ()):
            continue  # trivial SCC, no self-loop
        # walk one concrete cycle inside the SCC (every edge followed is
        # a real edge, so the finding's step list renders verbatim)
        start = min(comp)
        path = [start]
        seen = {start}

        def walk(v):
            for w in sorted(adj.get(v, ())):
                if w == start:
                    return True
                if w in compset and w not in seen:
                    seen.add(w)
                    path.append(w)
                    if walk(w):
                        return True
                    path.pop()
                    seen.discard(w)
            return False

        walk(start)
        cycles.append(path + [start])
    return cycles


def lock_order_findings(modules: dict, graph: Optional[dict] = None) -> list:
    graph = graph if graph is not None else build_lock_graph(modules)
    findings = []
    for cyc in _find_cycles(graph["edges"]):
        steps = []
        for a, b in zip(cyc, cyc[1:]):
            rp, line = graph["edges"][(a, b)]
            steps.append(f"{_render_lock(a)} -> {_render_lock(b)} "
                         f"({rp}:{line})")
        rp0, line0 = graph["edges"][(cyc[0], cyc[1])]
        findings.append(Finding(
            "host-lock-order", rp0, line0,
            "lock-acquisition-order cycle (a schedule exists that "
            "deadlocks, and deadlocks hang rather than error): "
            + "; ".join(steps),
        ))
    return findings


def _host_scope_modules(root: str) -> dict:
    out = {}
    for path in iter_source_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        if any(relpath.startswith(p) for p in HOST_SCOPE):
            try:
                out[relpath] = ast.parse(open(path).read())
            except (OSError, SyntaxError):
                continue
    return out


@rule(
    "host-lock-order",
    "the control-plane lock-acquisition-order graph (lock held -> lock "
    "acquired, following direct calls, module-level locks like "
    "chaos._LOCK included) must be acyclic: an inverted order deadlocks "
    "— it never errors — on the first unlucky schedule",
    lambda relpath: False,  # repo-level: runs via run_host_audit
    scope="repo-level over the host control-plane modules "
          "(run_host_audit / python -m dgraph_tpu.analysis.host)",
)
def check_host_lock_order(relpath: str, tree: ast.AST, lines: list,
                          root: str = ""):
    if not root:
        return []
    return lock_order_findings(_host_scope_modules(root))


# ---------------------------------------------------------------------------
# rule 3: host-durable-write
# ---------------------------------------------------------------------------


def _expr_durable(expr, tainted: set) -> Optional[str]:
    """Why ``expr`` names a durable artifact path (helper call, durable
    constant, or tainted name), or None."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            fname = _last_segment(node.func)
            if fname in DURABLE_PATH_FNS:
                return f"{fname}(...)"
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for hint in DURABLE_NAME_HINTS:
                if hint in node.value:
                    return f"{node.value!r}"
        if isinstance(node, ast.Name) and node.id in tainted:
            return f"name {node.id!r} (durable-path dataflow)"
    return None


def _open_write_mode(call: ast.Call) -> bool:
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and any(c in mode for c in "wax")


def durable_write_findings(relpath: str, tree: ast.AST, lines: list) -> list:
    findings = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if "atomic" in fn.name:
            continue  # the blessed writers' own tmp-file opens
        # local taint: names assigned from durable path expressions,
        # iterated to fixpoint (handles tmp = path + ".tmp")
        tainted: set = set()
        for _ in range(4):
            grew = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and _expr_durable(
                    node.value, tainted
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id not in tainted:
                            tainted.add(t.id)
                            grew = True
            if not grew:
                break
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = _last_segment(node.func)
            why = None
            if fname == "open" and node.args and _open_write_mode(node):
                why = _expr_durable(node.args[0], tainted)
                verb = "bare open(..., 'w')"
            elif fname in ("savez", "savez_compressed") and node.args:
                why = _expr_durable(node.args[0], tainted)
                verb = f"direct np.{fname}"
            if why:
                findings.append(Finding(
                    "host-durable-write", relpath, node.lineno,
                    f"{verb} to a durable artifact path ({why}) in "
                    f"{fn.name!r}: route through atomic_write_json / "
                    f"atomic_pickle_dump / atomic_savez — without the "
                    f"fsync+rename discipline a host crash can commit "
                    f"the name before the bytes (torn artifact under a "
                    f"valid name)",
                ))
    findings.sort(key=lambda f: f.line)
    return findings


@rule(
    "host-durable-write",
    "writes to durable artifacts (world.json/serving.json pointers, "
    "graph_g<N>.npz snapshots, plan-shard manifests, tuning records) "
    "must flow through atomic_write_json/atomic_pickle_dump/atomic_savez"
    " — a bare open(path,'w') or direct np.savez to such a path is a "
    "torn write waiting for a host crash",
    path_matcher(*DURABLE_SCOPE),
    scope="host control-plane modules + train/checkpoint.py, "
          "tune/record.py",
)
def check_host_durable_write(relpath: str, tree: ast.AST, lines: list):
    return durable_write_findings(relpath, tree, lines)


# ---------------------------------------------------------------------------
# rule 4: host-pointer-flip-last
# ---------------------------------------------------------------------------


def _is_pointer_write(call: ast.Call) -> bool:
    fname = _last_segment(call.func)
    if fname in POINTER_WRITE_CALLS:
        return True
    if fname in ("atomic_write_json", "atomic_savez") and call.args:
        for n in ast.walk(call.args[0]):
            if isinstance(n, ast.Call) and (
                _last_segment(n.func) == "world_path"
            ):
                return True
            if isinstance(n, ast.Constant) and isinstance(n.value, str) and (
                "world.json" in n.value or "serving.json" in n.value
            ):
                return True
    return False


def _child_blocks(stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if block:
            yield block
    for handler in getattr(stmt, "handlers", []):
        yield handler.body


def _chain_to_call(block, owner, call):
    """Path of (owner, block, idx) from ``block`` down to the innermost
    statement whose non-nested subtree contains ``call``."""
    for i, stmt in enumerate(block):
        if not any(n is call for n in ast.walk(stmt)):
            continue
        for child in _child_blocks(stmt):
            sub = _chain_to_call(child, stmt, call)
            if sub is not None:
                return [(owner, block, i)] + sub
        return [(owner, block, i)]
    return None


def _fs_effects_in(node) -> list:
    """(line, name) for filesystem-effect calls in ``node``, not
    descending into nested function definitions."""
    out = []
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not node:
            continue
        if isinstance(n, ast.Call):
            fname = _last_segment(n.func)
            if fname in FS_EFFECT_CALLS:
                out.append((n.lineno, fname))
            elif fname == "open" and n.args and _open_write_mode(n):
                out.append((n.lineno, "open(w)"))
        stack.extend(ast.iter_child_nodes(n))
    return out


def _effects_after_flip(path) -> list:
    """Filesystem effects reachable AFTER the pointer flip on the
    intra-procedural CFG: remaining statements of each enclosing block,
    loop back edges (unless the path returns/raises/breaks first), and
    try/finally bodies."""
    bad = []
    pending_break = False
    for level in range(len(path) - 1, -1, -1):
        owner, block, idx = path[level]
        exited = False
        for stmt in block[idx + 1:]:
            bad.extend(_fs_effects_in(stmt))
            if isinstance(stmt, (ast.Return, ast.Raise)):
                exited = True
                break
            if isinstance(stmt, ast.Break):
                pending_break = True
                break
            if isinstance(stmt, ast.Continue):
                break
        if exited:
            # the function exits on this path — but every ENCLOSING
            # try/finally still runs its finalbody after the return
            # (a finally that writes after the flip is exactly the
            # hidden-effect shape; pinned in tests/test_analysis_host)
            for o, _b, _i in path[: level + 1]:
                if isinstance(o, ast.Try):
                    for s in o.finalbody:
                        bad.extend(_fs_effects_in(s))
            return bad
        if isinstance(owner, (ast.For, ast.AsyncFor, ast.While)):
            if not pending_break:
                # back edge: the whole loop body may run again
                for s in owner.body:
                    bad.extend(_fs_effects_in(s))
            pending_break = False
        elif isinstance(owner, ast.Try):
            for s in owner.finalbody:
                bad.extend(_fs_effects_in(s))
    return bad


def pointer_flip_findings(relpath: str, tree: ast.AST, lines: list) -> list:
    findings = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        flips = [
            n for n in ast.walk(fn)
            if isinstance(n, ast.Call) and _is_pointer_write(n)
        ]
        for flip in flips:
            path = _chain_to_call(fn.body, fn, flip)
            if path is None:
                continue
            effects = _effects_after_flip(path)
            # the flip call itself re-found via a loop back edge is the
            # same single commit point, not a second effect
            effects = [e for e in effects if e[0] != flip.lineno]
            if effects:
                lst = ", ".join(f"{name}@{line}" for line, name in
                                sorted(set(effects))[:4])
                findings.append(Finding(
                    "host-pointer-flip-last", relpath, flip.lineno,
                    f"generation-pointer write in {fn.name!r} is not the "
                    f"last filesystem effect on some path to the exit "
                    f"({lst} can still run after the flip): a crash "
                    f"between the flip and the later write adopts a "
                    f"generation whose payload is not durable — the "
                    f"old-or-new-never-torn contract requires every "
                    f"artifact durable BEFORE the pointer moves",
                ))
    findings.sort(key=lambda f: f.line)
    return findings


@rule(
    "host-pointer-flip-last",
    "in a commit function, the generation-pointer write (write_world / "
    "atomic_write_json of a world_path) must be the LAST filesystem "
    "effect on every intra-procedural CFG path: payload durable before "
    "the pointer moves, or a crash adopts a torn generation",
    path_matcher(*HOST_SCOPE),
    scope="host control-plane modules (commit functions)",
)
def check_host_pointer_flip(relpath: str, tree: ast.AST, lines: list):
    return pointer_flip_findings(relpath, tree, lines)


# ---------------------------------------------------------------------------
# rule 5: host-chaos-coverage
# ---------------------------------------------------------------------------


def _known_points_from_tree(tree: ast.AST) -> dict:
    """``{point: line}`` parsed from a ``KNOWN_POINTS = {...}`` literal."""
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
            for t in node.targets
        ):
            if isinstance(node.value, ast.Dict):
                return {
                    k.value: k.lineno
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
    return {}


def _fire_sites(modules: dict) -> list:
    """``(point, relpath, line)`` for every ``chaos.fire("<point>")``
    call with a string-literal point across ``{relpath: tree}``."""
    sites = []
    for relpath, tree in modules.items():
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and _last_segment(node.func) == "fire"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                sites.append((node.args[0].value, relpath, node.lineno))
    return sites


def chaos_coverage_findings(
    root: Optional[str] = None,
    *,
    points: Optional[dict] = None,
    modules: Optional[dict] = None,
) -> list:
    """Bidirectional KNOWN_POINTS <-> fire-site drift check.  With
    ``root`` given, both sides come from the tree; tests pass explicit
    ``points`` (``{name: line}``) and ``modules`` (``{relpath: ast}``)."""
    if points is None or modules is None:
        root = root or repo_root()
        chaos_path = os.path.join(root, "dgraph_tpu", "chaos",
                                  "__init__.py")
        parsed_points = _known_points_from_tree(
            ast.parse(open(chaos_path).read())
        )
        all_modules = {}
        for path in iter_source_files(root):
            relpath = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                all_modules[relpath] = ast.parse(open(path).read())
            except (OSError, SyntaxError):
                continue
        points = parsed_points if points is None else points
        modules = all_modules if modules is None else modules
    sites = _fire_sites(modules)
    findings = []
    fired = {}
    for point, relpath, line in sites:
        fired.setdefault(point, []).append((relpath, line))
        if point not in points:
            findings.append(Finding(
                "host-chaos-coverage", relpath, line,
                f"chaos.fire({point!r}) names a point KNOWN_POINTS does "
                f"not register: the clause grammar rejects it at arm "
                f"time, so this site is permanently inert — register "
                f"the point or fix the name",
            ))
    for point, line in sorted(points.items()):
        real = [
            (rp, ln) for rp, ln in fired.get(point, [])
            if not rp.startswith("dgraph_tpu/chaos/")
        ]
        if not real:
            findings.append(Finding(
                "host-chaos-coverage", "dgraph_tpu/chaos/__init__.py",
                line,
                f"KNOWN_POINTS entry {point!r} has no fire site outside "
                f"dgraph_tpu/chaos/: a clause naming it parses but "
                f"never fires — the registry documents a boundary that "
                f"does not exist",
            ))
    return findings


@rule(
    "host-chaos-coverage",
    "bidirectional chaos-registry drift check: every KNOWN_POINTS entry "
    "must have a chaos.fire site outside dgraph_tpu/chaos/, and every "
    "fire site must name a registered point (an unregistered site is "
    "permanently inert; an unfired point is documentation, not "
    "coverage)",
    lambda relpath: False,  # repo-level: runs via run_host_audit
    scope="repo-level: chaos/__init__.py KNOWN_POINTS vs every "
          "dgraph_tpu fire site",
)
def check_host_chaos_coverage(relpath: str, tree: ast.AST, lines: list,
                              root: str = ""):
    if not root:
        return []
    return chaos_coverage_findings(root)


HOST_FILE_RULES = (
    "host-lock-discipline", "host-durable-write", "host-pointer-flip-last",
)
HOST_REPO_RULES = ("host-lock-order", "host-chaos-coverage")
HOST_RULES = HOST_FILE_RULES + HOST_REPO_RULES


# ---------------------------------------------------------------------------
# the audit runner
# ---------------------------------------------------------------------------


def run_host_audit(root: Optional[str] = None,
                   file_rules: bool = True) -> dict:
    """Audit the tree: per-file host rules (pragma-aware, via the lint
    machinery) plus the repo-level lock-order and chaos-coverage checks.
    ``file_rules=False`` skips the per-file pass — the analysis CLI's
    default mode uses that, because its lint pass already ran them."""
    from dgraph_tpu.analysis.lint import RULES

    root = root or repo_root()
    findings = []
    files_checked = 0
    # ONE parse of the tree feeds every repo-level check (chaos coverage
    # needs all modules; the lock graph the host-scope subset)
    all_modules: dict = {}
    for path in iter_source_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            all_modules[relpath] = ast.parse(open(path).read())
        except (OSError, SyntaxError):
            continue
    if file_rules:
        rules = {name: RULES[name] for name in HOST_FILE_RULES}
        for path in iter_source_files(root):
            relpath = os.path.relpath(path, root).replace(os.sep, "/")
            if not any(relpath.startswith(p) for p in DURABLE_SCOPE):
                continue
            files_checked += 1
            findings.extend(lint_file(path, root, rules))
    modules = {
        rp: t for rp, t in all_modules.items()
        if any(rp.startswith(p) for p in HOST_SCOPE)
    }
    graph = build_lock_graph(modules)
    findings.extend(lock_order_findings(modules, graph))
    points = _known_points_from_tree(
        all_modules.get("dgraph_tpu/chaos/__init__.py", ast.parse(""))
    )
    findings.extend(
        chaos_coverage_findings(points=points, modules=all_modules)
    )
    findings.sort(key=lambda f: (f.path, f.line))
    per_rule: dict = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    # structural summary: guarded-field sets per class (the evidence the
    # race rule is not vacuously inferring nothing) + the lock graph —
    # reusing the scans the lock graph already computed
    classes = {}
    for relpath in sorted(modules):
        ms = graph["scans"][relpath]
        for cs in ms.classes.values():
            if not cs.lock_attrs:
                continue
            _blessed, evidence = _held_extras(cs)
            guarded = set()
            for mname, scan in cs.methods.items():
                if mname == "__init__":
                    continue
                for field, kind, _line, held in scan.accesses:
                    if kind == "write" and (
                        frozenset(held) | evidence.get(mname, frozenset())
                    ):
                        guarded.add(field)
            classes[f"{relpath}::{cs.name}"] = {
                "locks": sorted(cs.lock_attrs),
                "guarded_fields": sorted(guarded),
            }
    return {
        "kind": "host_audit",
        "root": root,
        "files_checked": files_checked,
        "rules": list(HOST_RULES),
        "findings": [f.to_dict() for f in findings],
        "per_rule": per_rule,
        "failures": [
            f"{f.rule} {f.path}:{f.line}: {f.message}" for f in findings
        ],
        "classes": classes,
        "lock_edges": sorted(
            f"{_render_lock(a)} -> {_render_lock(b)}"
            for (a, b) in graph["edges"]
        ),
        "chaos_points": len(points),
        "ok": not findings,
    }


def chaos_points(root: Optional[str] = None) -> dict:
    """``{point: line}`` from the tree's chaos registry."""
    root = root or repo_root()
    path = os.path.join(root, "dgraph_tpu", "chaos", "__init__.py")
    return _known_points_from_tree(ast.parse(open(path).read()))


# ---------------------------------------------------------------------------
# selftest: fixture pairs + vacuity mutants
# ---------------------------------------------------------------------------

# every bad fixture is a faithful miniature of a REAL pre-audit shape in
# this tree (the first clean-tree run surfaced each; the fixes are pinned
# in tests/test_analysis_host.py) — they double as the vacuity mutants:
# a green clean-tree audit is only evidence while these stay RED.

_LOCK_FIXTURE = {
    "path": "dgraph_tpu/serve/batcher.py",
    # the pre-fix MicroBatcher shape: _inflight written under the cv in
    # _collect, then reset WITHOUT it from the worker loop
    "bad": (
        "import threading\n"
        "class Batcher:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self._inflight = []\n"
        "    def _collect(self):\n"
        "        with self._cv:\n"
        "            batch = self._inflight = []\n"
        "        return batch\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            self._collect()\n"
        "            self._inflight = []\n"
    ),
    "good": (
        "import threading\n"
        "class Batcher:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self._inflight = []\n"
        "    def _collect(self):\n"
        "        with self._cv:\n"
        "            batch = self._inflight = []\n"
        "        return batch\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            self._collect()\n"
        "            with self._cv:\n"
        "                self._inflight = []\n"
    ),
}

# thread-escape: the enclosing `with` must NOT bless a nested Thread
# target's body
_THREAD_ESCAPE_BAD = (
    "import threading\n"
    "class Engine:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.state = 0\n"
    "    def start(self):\n"
    "        with self._lock:\n"
    "            self.state = 1\n"
    "            def worker():\n"
    "                self.state = 2\n"
    "            threading.Thread(target=worker).start()\n"
)

_ORDER_FIXTURE = {
    # seeded two-lock cycle across two classes: A holds la and calls into
    # B (acquires lb); B holds lb and calls back into A (acquires la)
    "bad": {
        "a.py": (
            "import threading\n"
            "class A:\n"
            "    def __init__(self, b):\n"
            "        self._la = threading.Lock()\n"
            "        self.b = b\n"
            "    def f(self):\n"
            "        with self._la:\n"
            "            self.b.g_of_b()\n"
            "    def h_of_a(self):\n"
            "        with self._la:\n"
            "            pass\n"
        ),
        "b.py": (
            "import threading\n"
            "class B:\n"
            "    def __init__(self, a):\n"
            "        self._lb = threading.Lock()\n"
            "        self.a = a\n"
            "    def g_of_b(self):\n"
            "        with self._lb:\n"
            "            self.a.h_of_a()\n"
        ),
    },
    "good": {
        "a.py": (
            "import threading\n"
            "class A:\n"
            "    def __init__(self, b):\n"
            "        self._la = threading.Lock()\n"
            "        self.b = b\n"
            "    def f(self):\n"
            "        with self._la:\n"
            "            self.b.g_of_b()\n"
        ),
        "b.py": (
            "import threading\n"
            "class B:\n"
            "    def __init__(self):\n"
            "        self._lb = threading.Lock()\n"
            "    def g_of_b(self):\n"
            "        with self._lb:\n"
            "            pass\n"
        ),
    },
    # a three-lock cycle whose walk from its minimum lock is NOT
    # monotone in the lock ordering (la -> lc -> lb -> la): the class of
    # cycle a path-enumeration shortcut once missed — SCC detection must
    # keep finding it
    "bad3": {
        "m1.py": (
            "import threading\n"
            "class A:\n"
            "    def __init__(self, c):\n"
            "        self._la = threading.Lock()\n"
            "        self.c = c\n"
            "    def f_of_a(self):\n"
            "        with self._la:\n"
            "            self.c.g_of_c()\n"
            "    def t_of_a(self):\n"
            "        with self._la:\n"
            "            pass\n"
        ),
        "m2.py": (
            "import threading\n"
            "class C:\n"
            "    def __init__(self, b):\n"
            "        self._lc = threading.Lock()\n"
            "        self.b = b\n"
            "    def g_of_c(self):\n"
            "        with self._lc:\n"
            "            self.b.h_of_b()\n"
        ),
        "m3.py": (
            "import threading\n"
            "class B:\n"
            "    def __init__(self, a):\n"
            "        self._lb = threading.Lock()\n"
            "        self.a = a\n"
            "    def h_of_b(self):\n"
            "        with self._lb:\n"
            "            self.a.t_of_a()\n"
        ),
    },
}

_DURABLE_FIXTURE = {
    "path": "dgraph_tpu/train/shrink.py",
    # the pre-fix shrink shape: np.savez straight onto graph_path, and a
    # bare open onto the manifest
    "bad": (
        "import numpy as np\n"
        "def snapshot(run_dir, gen, edges):\n"
        "    np.savez(graph_path(run_dir, gen), edge_index=edges)\n"
        "def tamper(plan_dir):\n"
        "    mpath = manifest_path(plan_dir)\n"
        "    open(mpath, 'w').write('{}')\n"
    ),
    "good": (
        "from dgraph_tpu.plan_shards import atomic_savez, atomic_write_json\n"
        "def snapshot(run_dir, gen, edges):\n"
        "    atomic_savez(graph_path(run_dir, gen), edge_index=edges)\n"
        "def write(plan_dir, man):\n"
        "    atomic_write_json(manifest_path(plan_dir), man)\n"
    ),
}

_LEDGER_DURABLE_FIXTURE = {
    "path": "dgraph_tpu/obs/ledger.py",
    # a bare append onto the ledger: a host crash mid-write tears the
    # line with nothing fsynced behind it
    "bad": (
        "import json\n"
        "def append(d, recs):\n"
        "    fh = open(ledger_path(d), 'a')\n"
        "    for r in recs:\n"
        "        fh.write(json.dumps(r) + '\\n')\n"
    ),
    # the blessed shape: the append writer flush+fsyncs before returning
    "good": (
        "def append(d, recs):\n"
        "    atomic_append_jsonl(ledger_path(d), recs)\n"
    ),
}

_FLIP_FIXTURE = {
    "path": "dgraph_tpu/train/shrink.py",
    # pointer-flip-before-payload: the world pointer moves, THEN the
    # graph snapshot lands — a crash between the two adopts a torn world
    "bad": (
        "import numpy as np\n"
        "def commit(run_dir, rec, edges):\n"
        "    write_world(run_dir, rec)\n"
        "    np.savez(graph_path(run_dir, 1), edge_index=edges)\n"
    ),
    # the replan shape: flip-then-return inside a retry loop whose body
    # rebuilds artifacts — the back edge never follows the flip
    "good": (
        "def commit(run_dir, rec, build):\n"
        "    for _ in range(5):\n"
        "        build()\n"
        "        if ready(run_dir):\n"
        "            write_world(run_dir, rec)\n"
        "            return rec\n"
        "    raise RuntimeError('quiesce appends')\n"
    ),
    # a finally body runs AFTER the post-flip return — hidden payload
    # write the early-return walk once missed
    "bad_finally": (
        "import os\n"
        "def commit(run_dir, rec, tmp, path):\n"
        "    try:\n"
        "        write_world(run_dir, rec)\n"
        "        return rec\n"
        "    finally:\n"
        "        os.replace(tmp, path)\n"
    ),
}

_CHAOS_FIXTURE = {
    # unregistered fire site + uncovered registry point
    "points": {"ckpt.save": 10, "serve.ghost": 11},
    "bad_modules": {
        "dgraph_tpu/train/checkpoint.py":
            "def save():\n    chaos.fire('ckpt.save')\n",
        "dgraph_tpu/serve/engine.py":
            "def infer():\n    chaos.fire('serve.typo')\n",
    },
    "good_points": {"ckpt.save": 10},
    "good_modules": {
        "dgraph_tpu/train/checkpoint.py":
            "def save():\n    chaos.fire('ckpt.save')\n",
    },
}


def host_selftest_failures(root: Optional[str] = None) -> list:
    """Every failure string the host tier's selftest produces: per-rule
    fixture pairs, the vacuity mutants (each must go RED), pragma
    support, real-tree structural pins, and the clean-tree audit."""
    from dgraph_tpu.analysis.lint import RULES, _suppressed

    failures: list = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    def run_file_rule(name, path, src):
        tree = ast.parse(src)
        return RULES[name].check(path, tree, src.splitlines())

    # --- host-lock-discipline: fixture pair + thread escape ---
    got = run_file_rule("host-lock-discipline", _LOCK_FIXTURE["path"],
                        _LOCK_FIXTURE["bad"])
    check(got, "host-lock-discipline missed an unlocked guarded-field "
               "write (vacuity mutant stayed GREEN)")
    got = run_file_rule("host-lock-discipline", _LOCK_FIXTURE["path"],
                        _LOCK_FIXTURE["good"])
    check(not got, f"host-lock-discipline false-positived on locked "
                   f"code: {got}")
    got = run_file_rule("host-lock-discipline", "dgraph_tpu/serve/x.py",
                        _THREAD_ESCAPE_BAD)
    check(got, "host-lock-discipline treated a nested Thread target as "
               "covered by the enclosing with-lock (thread escape)")

    # --- host-lock-order: seeded cycle RED, acyclic GREEN ---
    bad = {p: ast.parse(s) for p, s in _ORDER_FIXTURE["bad"].items()}
    got = lock_order_findings(bad)
    check(got, "host-lock-order missed a seeded two-lock cycle "
               "(vacuity mutant stayed GREEN)")
    good = {p: ast.parse(s) for p, s in _ORDER_FIXTURE["good"].items()}
    got = lock_order_findings(good)
    check(not got, f"host-lock-order false-positived on an acyclic "
                   f"graph: {got}")
    bad3 = {p: ast.parse(s) for p, s in _ORDER_FIXTURE["bad3"].items()}
    got = lock_order_findings(bad3)
    check(got, "host-lock-order missed a non-monotone three-lock cycle "
               "(the SCC detector regressed to path enumeration)")

    # --- host-durable-write ---
    got = run_file_rule("host-durable-write", _DURABLE_FIXTURE["path"],
                        _DURABLE_FIXTURE["bad"])
    check(len(got) >= 2, "host-durable-write missed a bare "
                         "open/np.savez onto a durable path (vacuity "
                         "mutant stayed GREEN)")
    got = run_file_rule("host-durable-write", _DURABLE_FIXTURE["path"],
                        _DURABLE_FIXTURE["good"])
    check(not got, f"host-durable-write false-positived on the atomic "
                   f"writers: {got}")
    got = run_file_rule("host-durable-write",
                        _LEDGER_DURABLE_FIXTURE["path"],
                        _LEDGER_DURABLE_FIXTURE["bad"])
    check(got, "host-durable-write missed a bare open(ledger_path, 'a') "
               "(ledger-append vacuity mutant stayed GREEN)")
    got = run_file_rule("host-durable-write",
                        _LEDGER_DURABLE_FIXTURE["path"],
                        _LEDGER_DURABLE_FIXTURE["good"])
    check(not got, f"host-durable-write false-positived on "
                   f"atomic_append_jsonl: {got}")

    # --- host-pointer-flip-last ---
    got = run_file_rule("host-pointer-flip-last", _FLIP_FIXTURE["path"],
                        _FLIP_FIXTURE["bad"])
    check(got, "host-pointer-flip-last missed a flip-before-payload "
               "(vacuity mutant stayed GREEN)")
    got = run_file_rule("host-pointer-flip-last", _FLIP_FIXTURE["path"],
                        _FLIP_FIXTURE["good"])
    check(not got, f"host-pointer-flip-last false-positived on the "
                   f"flip-then-return retry loop: {got}")
    got = run_file_rule("host-pointer-flip-last", _FLIP_FIXTURE["path"],
                        _FLIP_FIXTURE["bad_finally"])
    check(got, "host-pointer-flip-last missed a try/finally payload "
               "write running after the post-flip return")

    # --- host-chaos-coverage ---
    got = chaos_coverage_findings(
        points=_CHAOS_FIXTURE["points"],
        modules={p: ast.parse(s)
                 for p, s in _CHAOS_FIXTURE["bad_modules"].items()},
    )
    check(
        any("serve.typo" in f.message for f in got),
        "host-chaos-coverage missed an unregistered fire site (vacuity "
        "mutant stayed GREEN)",
    )
    check(
        any("serve.ghost" in f.message for f in got),
        "host-chaos-coverage missed a registered point with no fire site",
    )
    got = chaos_coverage_findings(
        points=_CHAOS_FIXTURE["good_points"],
        modules={p: ast.parse(s)
                 for p, s in _CHAOS_FIXTURE["good_modules"].items()},
    )
    check(not got, f"host-chaos-coverage false-positived on a matched "
                   f"registry: {got}")

    # --- pragma shares lint's plumbing ---
    src = _LOCK_FIXTURE["bad"].replace(
        "            self._inflight = []\n",
        "            self._inflight = []"
        "  # lint: allow(host-lock-discipline)\n",
    )
    got = run_file_rule("host-lock-discipline", _LOCK_FIXTURE["path"], src)
    got = [f for f in got
           if not _suppressed(src.splitlines(), f.line, f.rule)]
    check(not got, "the lint pragma did not suppress a host finding")

    # --- real-tree structural pins (the graphs are not vacuously empty) ---
    root = root or repo_root()
    audit = run_host_audit(root)
    edges = audit["lock_edges"]
    check(
        any("MicroBatcher._cv" in e and "TenantTable._lock" in e
            for e in edges),
        f"lock graph lost the real batcher->tenancy edge: {edges}",
    )
    check(
        any("Membership._hb_lock" in e and "_LOCK" in e for e in edges),
        f"lock graph lost the real membership->chaos edge: {edges}",
    )
    eng = audit["classes"].get("dgraph_tpu/serve/engine.py::ServeEngine", {})
    check(
        {"degraded", "_batch", "_consecutive_failures"}
        <= set(eng.get("guarded_fields", [])),
        f"guarded-field inference lost the engine's lock contract: {eng}",
    )
    check(audit["chaos_points"] >= 10,
          f"chaos registry parse collapsed: {audit['chaos_points']} points")

    # --- the clean tree passes the full audit ---
    check(
        audit["ok"],
        "clean-tree host audit has findings: " + "; ".join(
            audit["failures"][:10]
        ),
    )
    return failures
