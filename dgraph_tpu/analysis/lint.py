"""Contract linter: stdlib-``ast`` rules for the repo's cross-layer contracts.

Each rule encodes one invariant that used to be enforced only by review
discipline (and in two cases was already silently broken when this linter
first ran — see the pinned regressions in ``tests/test_analysis.py``):

- ``jax-free-module`` — ``dgraph_tpu.chaos``, ``train/supervise.py`` and
  ``obs/health.py`` must never *use* jax: a wedged lease can hang any jax
  API call, and these are exactly the modules that must outlive a wedged
  child (the supervisor) or be loadable standalone without triggering a
  backend (bench's health loader).  The rule flags any ``import jax`` in
  those files (any scope) and any import of a ``dgraph_tpu`` module whose
  own module level imports jax.  The package ``__init__`` is exempt by
  design: normal package imports pay it, but the standalone loaders load
  these files by path precisely to skip it, so the contract is about the
  modules' OWN code.
- ``no-config-read-in-trace`` — no ``dgraph_tpu.config`` attribute read or
  ``os.environ`` access lexically inside a function that is passed to (or
  decorated with) ``jit`` / ``shard_map`` / ``custom_vjp`` / ``grad`` /
  ``scan`` and friends.  This is the PR 4 mixed-lowering hazard, machine
  checked: a config read at trace time can hand two legs of one op
  different lowerings, and a cached executable silently ignores later
  flag flips.  Resolve once OUTSIDE the traced function and thread the
  decision through as a static argument (``comm.collectives.
  resolve_plan_impl`` is the pattern).
- ``custom-vjp-paired`` — every ``jax.custom_vjp`` function must call
  ``defvjp`` in the same file: an unpaired declaration traces fine and
  fails only when somebody differentiates through it.
- ``named-scope-on-collectives`` — every public function in
  ``comm/collectives.py`` that issues a ``lax`` collective must be wrapped
  in a named scope: un-scoped collectives are invisible in Perfetto
  traces, and perf attribution of the halo exchange is the whole point of
  the obs layer.
- ``no-nondeterminism-in-plan`` — plan/partition builds must be
  deterministic functions of (graph, seed): no unseeded RNG, no
  wall-clock reads.  Plans are content-addressed into an on-disk cache
  and signed by the tuner; a nondeterministic build breaks both.

Suppression: append ``# lint: allow(<rule-name>)`` on the offending line
(or the line above) — every suppression is a documented, greppable
decision, e.g. ``obs/health.py``'s opt-in backend snapshot.

Adding a rule: write ``check(path, tree, lines) -> list[Finding]``,
decorate with :func:`rule`, and add a fixture pair to the selftest in
``__main__.py`` (a snippet that must fire + one that must not).  Rules are
pure stdlib (``ast`` only) so the linter runs without jax anywhere.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Optional

# jax-free and stdlib-free by contract — the linter stays importable
# without jax anywhere (the one env-var name no-rank-branch-in-trace
# greps for lives in the same shared home its runtime readers use)
from dgraph_tpu.utils.env import RANK_ENV_VAR

# functions whose function-valued arguments are traced by jax: a config
# read inside one is a trace-time read (the PR 4 hazard class).
# pallas_call is one of them — the kernel body is traced like any jit
# body, and was this linter's blind spot until the pallas_p2p transport
# made kernels a live place for config reads/spans to hide.
TRACING_ENTRY_POINTS = frozenset({
    "jit", "shard_map", "custom_vjp", "custom_jvp", "grad", "value_and_grad",
    "vjp", "jvp", "linearize", "scan", "while_loop", "fori_loop", "cond",
    "checkpoint", "remat", "pmap", "vmap", "make_jaxpr", "eval_shape",
    "pallas_call",
})

# lax collectives that must appear only inside named scopes in the
# collectives facade (named-scope-on-collectives)
COLLECTIVE_CALLS = frozenset({
    "all_to_all", "ppermute", "psum", "pmean", "pmax", "pmin", "all_gather",
    "psum_scatter", "pshuffle",
})

_PRAGMA = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Rule:
    name: str
    description: str
    applies: Callable[[str], bool]  # repo-relative posix path -> bool
    check: Callable[[str, ast.AST, list], list]  # (relpath, tree, lines)
    # human-readable applies-to (what the `applies` predicate encodes) —
    # printed by ``--list-rules`` and machine-checked against the rule
    # catalog table in docs/static-analysis.md
    scope: str = ""


RULES: dict = {}


def rule(name: str, description: str, applies, scope: str = ""):
    """Register a rule. ``applies`` is a predicate over the repo-relative
    posix path (use :func:`path_matcher` for prefix/suffix sets);
    ``scope`` is its human-readable rendering for ``--list-rules`` and
    the docs table."""

    def deco(fn):
        RULES[name] = Rule(name, description, applies, fn, scope)
        return fn

    return deco


def path_matcher(*prefixes: str):
    def match(relpath: str) -> bool:
        return any(relpath.startswith(p) for p in prefixes)

    return match


def _suppressed(lines: list, lineno: int, rule_name: str) -> bool:
    """True when the finding's line (or the one above) carries
    ``# lint: allow(<rule>)`` for this rule."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _PRAGMA.search(lines[ln - 1])
            if m and rule_name in [s.strip() for s in m.group(1).split(",")]:
                return True
    return False


def _dotted(node) -> str:
    """Best-effort dotted name of an expression (``a.b.c`` -> "a.b.c")."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last_segment(node) -> str:
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else ""


# ---------------------------------------------------------------------------
# jax-free-module
# ---------------------------------------------------------------------------

JAX_FREE_TARGETS = (
    "dgraph_tpu/chaos/",
    "dgraph_tpu/train/supervise.py",
    "dgraph_tpu/obs/health.py",
    # the span tracer is imported by the supervisor and loaded standalone
    # by bench's wedge-surviving loader — same contract as health.py
    "dgraph_tpu/obs/spans.py",
    # shard/manifest integrity IO must run without a backend: the v8 plan
    # artifact is repaired/inspected on hosts where jax may be wedged
    "dgraph_tpu/plan_shards.py",
    # liveness is the thing that must keep working while jax is wedged:
    # heartbeats/polls/barriers/rendezvous never touch an accelerator API
    "dgraph_tpu/comm/membership.py",
    # the shared home of cross-boundary env-var names (RANK_ENV_VAR):
    # imported by every module above, so it must never pull jax in
    "dgraph_tpu/utils/env.py",
    # the package __init__ the env import pays on the way in: its heavy
    # exports (TimingReport, ExperimentLog) are PEP 562-lazy precisely so
    # this file stays jax-free at module level — enforcing it here means
    # a restored eager import turns every target above RED instead of
    # silently re-poisoning them
    "dgraph_tpu/utils/__init__.py",
    # serving control-plane bookkeeping: the model registry, tenant
    # quota table, and structured serve errors are inspected by the
    # supervisor and health tooling in processes that never dial a
    # backend — and the serve package __init__ is PEP 562-lazy for the
    # same reason utils' is (an eager engine import here would poison
    # all three)
    "dgraph_tpu/serve/__init__.py",
    "dgraph_tpu/serve/errors.py",
    "dgraph_tpu/serve/registry.py",
    "dgraph_tpu/serve/tenancy.py",
    # the host-side concurrency/durability auditor is stdlib-ast by
    # contract: it audits exactly the modules that must outlive a wedge,
    # so it must never need a backend to run
    "dgraph_tpu/analysis/host/",
    # the perf-trajectory ledger + drift sentinel + report: the
    # longitudinal store is read/written by bench's supervisor and by
    # operators on machines where jax is wedged or absent, so the whole
    # pipeline (normalize, gate, render) is stdlib-only by contract
    "dgraph_tpu/obs/ledger.py",
    "dgraph_tpu/obs/regress.py",
    "dgraph_tpu/obs/report.py",
    # the halo schedule compiler core (IR + passes + selftest): the
    # schedule is DATA — compiled, verified, serialized, and diffed on
    # hosts with no backend (plan tooling, regress, operators reading a
    # manifest), so everything except the executor stays stdlib-only.
    # comm/collectives.py replays the schedule and is the ONE jax
    # consumer, deliberately outside this list.
    "dgraph_tpu/sched/",
    # the grow-to-fit transition: the world-growth decision path (join
    # discovery, unfold, gather, adopt) must keep working while jax is
    # wedged — everything that pulls jax (plan builder, reshard kernel)
    # is reached through train/shrink.py's function-scope imports, and
    # the join announcement path rides membership.py (already a target)
    "dgraph_tpu/train/grow.py",
    # the wire-format registry, dedup planner, and their selftest: wire
    # formats are DATA (resolved, priced, serialized into plans and
    # tuning records) on the same backend-less hosts as the schedule
    # compiler — wire/codec.py holds the jax encode/decode pairs and is
    # deliberately outside this list (wire/__init__ lazy-exports it)
    "dgraph_tpu/wire/spec.py",
    "dgraph_tpu/wire/dedup.py",
    "dgraph_tpu/wire/__main__.py",
)


def _module_level_imports(tree: ast.AST):
    """(node, module) pairs for imports executed at module import time —
    top-level statements, descending into top-level ``if``/``try`` blocks
    (guarded imports still run at import time)."""
    out = []
    stack = list(getattr(tree, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Import):
            out.extend((node, a.name) for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0 and node.module:
                out.append((node, node.module))
        elif isinstance(node, (ast.If, ast.Try, ast.With)):
            for field in ("body", "orelse", "finalbody"):
                stack.extend(getattr(node, field, []))
            for handler in getattr(node, "handlers", []):
                stack.extend(handler.body)
    return out


def _all_imports(tree: ast.AST):
    """(node, module, names) for every import anywhere in the file."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.append((node, a.name, ()))
        elif isinstance(node, ast.ImportFrom) and node.level == 0 and node.module:
            out.append((node, node.module, tuple(a.name for a in node.names)))
    return out


def _module_file(root: str, dotted: str) -> Optional[str]:
    """Resolve a dotted module path to a file under ``root`` (or None for
    third-party / stdlib modules)."""
    base = os.path.join(root, *dotted.split("."))
    for cand in (base + ".py", os.path.join(base, "__init__.py")):
        if os.path.isfile(cand):
            return cand
    return None


def _file_uses_jax_at_module_level(root: str, path: str, _seen=None) -> bool:
    """True when importing ``path`` as a module pulls jax in, following
    package-internal module-level imports transitively. The top-level
    package ``__init__`` files are skipped (see module docstring)."""
    _seen = _seen if _seen is not None else set()
    if path in _seen:
        return False
    _seen.add(path)
    try:
        tree = ast.parse(open(path).read())
    except (OSError, SyntaxError):
        return False
    for _node, mod in _module_level_imports(tree):
        if mod == "jax" or mod.startswith("jax."):
            return True
        if mod.startswith("dgraph_tpu"):
            dep = _module_file(root, mod)
            if dep and not dep.endswith(os.path.join("dgraph_tpu", "__init__.py")):
                if _file_uses_jax_at_module_level(root, dep, _seen):
                    return True
    return False


@rule(
    "jax-free-module",
    "chaos/, train/supervise.py and obs/health.py must not use jax in any "
    "scope, nor import dgraph_tpu modules that use jax at module level",
    path_matcher(*JAX_FREE_TARGETS),
    scope=", ".join(t.replace("dgraph_tpu/", "") for t in JAX_FREE_TARGETS),
)
def check_jax_free(relpath: str, tree: ast.AST, lines: list, root: str = ""):
    findings = []
    for node, mod, names in _all_imports(tree):
        if mod == "jax" or mod.startswith("jax."):
            findings.append(Finding(
                "jax-free-module", relpath, node.lineno,
                f"import of {mod!r} in a jax-free module (a wedged lease can "
                f"hang any jax call; this module must outlive one)",
            ))
            continue
        targets = []
        if mod.startswith("dgraph_tpu"):
            targets.append(mod)
            # `from dgraph_tpu.x import y` may name a submodule y
            targets.extend(f"{mod}.{n}" for n in names)
        for t in targets:
            dep = _module_file(root, t) if root else None
            if (
                dep
                and not dep.endswith(os.path.join("dgraph_tpu", "__init__.py"))
                and _file_uses_jax_at_module_level(root, dep)
            ):
                findings.append(Finding(
                    "jax-free-module", relpath, node.lineno,
                    f"import of {t!r}, whose module level pulls in jax",
                ))
                break
    return findings


# ---------------------------------------------------------------------------
# no-config-read-in-trace
# ---------------------------------------------------------------------------


def _config_aliases(tree: ast.AST) -> set:
    """Names bound to the ``dgraph_tpu.config`` module anywhere in the
    file (``from dgraph_tpu import config as _cfg``, ``import
    dgraph_tpu.config as cfg``, ...)."""
    aliases = set()
    for node, mod, _names in _all_imports(tree):
        if isinstance(node, ast.ImportFrom):
            if mod == "dgraph_tpu":
                for a in node.names:
                    if a.name == "config":
                        aliases.add(a.asname or a.name)
        else:
            for a in node.names:
                if a.name == "dgraph_tpu.config" and a.asname:
                    aliases.add(a.asname)
    return aliases


def _partial_target(call: ast.Call):
    """The function NAME a ``functools.partial(fn, ...)`` call binds, or
    None — pallas kernels reach ``pallas_call`` through exactly this
    wrapper (static kwargs baked in), so the descent must see through
    it."""
    if _last_segment(call.func) != "partial" or not call.args:
        return None
    first = call.args[0]
    return first.id if isinstance(first, ast.Name) else None


def _traced_functions(tree: ast.AST) -> list:
    """Function nodes handed to jax tracing machinery: decorated with a
    tracing entry point, or passed (by name, inline lambda, inline
    ``functools.partial``, or a name bound to a partial) as an argument
    to one. ``pallas_call`` kernels count — directly or through a
    ``kern = functools.partial(kernel_fn, ...)`` alias."""
    traced, by_name, partial_alias = [], {}, {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _last_segment(target) in TRACING_ENTRY_POINTS:
                    traced.append(node)
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # kern = functools.partial(kernel_fn, ...) -> kern aliases it
            fn_name = _partial_target(node.value)
            if fn_name:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        partial_alias[t.id] = fn_name
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(node.func) not in TRACING_ENTRY_POINTS:
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Lambda):
                traced.append(arg)
            elif isinstance(arg, ast.Name):
                traced.extend(by_name.get(arg.id, []))
                traced.extend(by_name.get(partial_alias.get(arg.id, ""), []))
            elif isinstance(arg, ast.Call):
                fn_name = _partial_target(arg)
                if fn_name:
                    traced.extend(by_name.get(fn_name, []))
    return traced


@rule(
    "no-config-read-in-trace",
    "no dgraph_tpu.config / os.environ read lexically inside a function "
    "passed to jit/shard_map/custom_vjp/... (the PR 4 mixed-lowering "
    "hazard: resolve before the trace, thread the decision through)",
    path_matcher("dgraph_tpu/"),
    scope="dgraph_tpu/",
)
def check_config_read_in_trace(relpath: str, tree: ast.AST, lines: list):
    aliases = _config_aliases(tree)
    findings = []
    for fn in _traced_functions(tree):
        for node in ast.walk(fn):
            bad = None
            if isinstance(node, ast.Attribute):
                base = _dotted(node.value)
                if base in aliases:
                    bad = f"config read '{base}.{node.attr}'"
                elif base == "os" and node.attr in ("environ", "getenv"):
                    bad = f"environment read 'os.{node.attr}'"
            elif isinstance(node, ast.ImportFrom) and (
                node.module == "dgraph_tpu"
                and any(a.name == "config" for a in node.names)
                or node.module == "dgraph_tpu.config"
            ):
                bad = "dgraph_tpu.config imported"
            elif isinstance(node, ast.Import) and any(
                a.name == "dgraph_tpu.config" for a in node.names
            ):
                bad = "dgraph_tpu.config imported"
            if bad:
                findings.append(Finding(
                    "no-config-read-in-trace", relpath, node.lineno,
                    f"{bad} inside traced function "
                    f"{getattr(fn, 'name', '<lambda>')!r} (line {fn.lineno}): "
                    f"a trace-time read freezes into the executable and can "
                    f"desynchronize legs of one op",
                ))
    return findings


# ---------------------------------------------------------------------------
# no-span-in-trace
# ---------------------------------------------------------------------------

# host-side span/timer entry points (obs.spans / utils.timing) that must
# never execute inside a traced body: a host clock read there measures
# TRACING (once), not execution (every step), and a span id would freeze
# into the cached executable — both silently wrong, never crashing
SPAN_CALLS = frozenset({"span", "start_span"})
TIMER_CALLS = frozenset({"start", "stop", "time", "add_time"})
PROFILER_CALLS = frozenset({"trace_to"})


@rule(
    "no-span-in-trace",
    "no obs.spans span / TimingReport timer / profiler call lexically "
    "inside a function passed to jit/shard_map/scan/... (host timing in a "
    "traced body measures tracing, not execution; spans stay at host "
    "boundaries)",
    path_matcher("dgraph_tpu/"),
    scope="dgraph_tpu/",
)
def check_span_in_trace(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for fn in _traced_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            last = _last_segment(node.func)
            bad = None
            if last in SPAN_CALLS:
                # only span-shaped calls: a string name argument or
                # keyword attrs (filters regex Match.span(int) lookalikes)
                named = any(
                    isinstance(a, ast.Constant) and isinstance(a.value, str)
                    for a in node.args
                ) or bool(node.keywords)
                if named:
                    bad = f"span call '{dotted or last}'"
            elif dotted.startswith("TimingReport.") and last in TIMER_CALLS:
                bad = f"host timer call '{dotted}'"
            elif last in PROFILER_CALLS:
                bad = f"profiler context '{dotted or last}'"
            if bad:
                findings.append(Finding(
                    "no-span-in-trace", relpath, node.lineno,
                    f"{bad} inside traced function "
                    f"{getattr(fn, 'name', '<lambda>')!r} (line {fn.lineno}):"
                    f" host-side timing inside a jit/shard_map/scan body "
                    f"runs at trace time, not per step — move it outside "
                    f"the traced boundary",
                ))
    return findings


# ---------------------------------------------------------------------------
# no-rank-branch-in-trace
# ---------------------------------------------------------------------------

# call names that return this process's rank identity
RANK_IDENTITY_CALLS = frozenset({"process_index", "rank_from_env"})


def _rank_env_aliases(tree: ast.AST) -> set:
    """Names bound to RANK_ENV_VAR in this file (``from dgraph_tpu.utils.
    env import RANK_ENV_VAR [as ...]`` — chaos re-exports it too)."""
    aliases = set()
    for node, mod, _names in _all_imports(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        if mod in ("dgraph_tpu.utils.env", "dgraph_tpu.chaos",
                   "dgraph_tpu.utils"):
            for a in node.names:
                if a.name == "RANK_ENV_VAR":
                    aliases.add(a.asname or a.name)
    return aliases


def _rank_read(expr: ast.AST, env_aliases: set, cfg_aliases: set):
    """The rank-identity read inside ``expr``, or None: a
    ``jax.process_index()``-family call, a ``$DGRAPH_RANK`` env read (by
    literal or by RANK_ENV_VAR alias), or a rank field on the config
    module."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and (
            _last_segment(sub.func) in RANK_IDENTITY_CALLS
        ):
            return f"'{_dotted(sub.func) or _last_segment(sub.func)}()'", sub
        if isinstance(sub, ast.Constant) and sub.value == RANK_ENV_VAR:
            return f"'{RANK_ENV_VAR}' environment read", sub
        if isinstance(sub, ast.Name) and sub.id in env_aliases:
            return f"'{sub.id}' (RANK_ENV_VAR) environment read", sub
        if isinstance(sub, ast.Attribute) and sub.attr == "RANK_ENV_VAR":
            return "'RANK_ENV_VAR' environment read", sub
        if (
            isinstance(sub, ast.Attribute)
            and _dotted(sub.value) in cfg_aliases
            and "rank" in sub.attr.lower()
        ):
            return f"config rank field '{_dotted(sub.value)}.{sub.attr}'", sub
    return None


def _control_flow_exprs(fn: ast.AST):
    """Expressions that steer PYTHON control flow (or indexing) inside a
    function body: a per-rank value here changes what gets TRACED, not
    what gets computed — every rank builds a different program."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            yield node.test
        elif isinstance(node, ast.For):
            yield node.iter
        elif isinstance(node, ast.Subscript):
            yield node.slice
        elif isinstance(node, ast.comprehension):
            yield node.iter
            yield from node.ifs


@rule(
    "no-rank-branch-in-trace",
    "no DGRAPH_RANK / jax.process_index() / config rank-field read inside "
    "Python control flow of a function passed to jit/shard_map/... — every "
    "rank would trace a DIFFERENT program, and mismatched collective "
    "schedules deadlock (not error) on real transports; resolve rank-"
    "dependent decisions on the host, outside the traced boundary",
    path_matcher("dgraph_tpu/"),
    scope="dgraph_tpu/",
)
def check_rank_branch_in_trace(relpath: str, tree: ast.AST, lines: list):
    env_aliases = _rank_env_aliases(tree)
    cfg_aliases = _config_aliases(tree)
    findings = []
    seen = set()
    for fn in _traced_functions(tree):
        for expr in _control_flow_exprs(fn):
            hit = _rank_read(expr, env_aliases, cfg_aliases)
            if hit is None:
                continue
            why, node = hit
            key = (node.lineno, node.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                "no-rank-branch-in-trace", relpath, node.lineno,
                f"{why} steering Python control flow inside traced "
                f"function {getattr(fn, 'name', '<lambda>')!r} (line "
                f"{fn.lineno}): each rank traces a different program — "
                f"trace-time SPMD divergence, the collective-schedule "
                f"deadlock analysis.spmd exists to catch, here at its "
                f"source",
            ))
    return findings


# ---------------------------------------------------------------------------
# custom-vjp-paired
# ---------------------------------------------------------------------------


@rule(
    "custom-vjp-paired",
    "every jax.custom_vjp declaration must have a defvjp call in the same "
    "file (an unpaired one only fails under differentiation)",
    path_matcher("dgraph_tpu/"),
    scope="dgraph_tpu/",
)
def check_custom_vjp_paired(relpath: str, tree: ast.AST, lines: list):
    declared = {}  # name -> lineno
    paired = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _last_segment(target) == "custom_vjp":
                    declared[node.name] = node.lineno
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _last_segment(node.value.func) == "custom_vjp":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        declared[t.id] = node.lineno
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "defvjp":
                paired.add(_dotted(node.func.value))
    return [
        Finding(
            "custom-vjp-paired", relpath, line,
            f"custom_vjp function {name!r} has no defvjp call in this file",
        )
        for name, line in sorted(declared.items(), key=lambda kv: kv[1])
        if name not in paired
    ]


# ---------------------------------------------------------------------------
# named-scope-on-collectives
# ---------------------------------------------------------------------------


@rule(
    "named-scope-on-collectives",
    "public functions in comm/collectives.py that issue a lax collective "
    "must be wrapped in a named scope (profiler attribution)",
    path_matcher("dgraph_tpu/comm/collectives.py"),
    scope="comm/collectives.py",
)
def check_named_scope(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.startswith("_"):
            continue
        issues = [
            sub.lineno
            for sub in ast.walk(node)
            if isinstance(sub, ast.Call)
            and _last_segment(sub.func) in COLLECTIVE_CALLS
        ]
        if not issues:
            continue
        scoped = any(
            _last_segment(dec.func if isinstance(dec, ast.Call) else dec)
            in ("named_scope", "_scoped")
            for dec in node.decorator_list
        )
        if not scoped:
            findings.append(Finding(
                "named-scope-on-collectives", relpath, node.lineno,
                f"public collective {node.name!r} (issues a collective at "
                f"line {issues[0]}) is not wrapped in a named scope",
            ))
    return findings


# ---------------------------------------------------------------------------
# no-unchecked-shard-map
# ---------------------------------------------------------------------------


@rule(
    "no-unchecked-shard-map",
    "every shard_map call site routes its replication-check kwargs through "
    "comm.collectives.shard_map_checks(...): a raw check_vma/check_rep "
    "kwarg (or a blanket **RELAXED_CHECKS splat) silently disables the one "
    "checker that catches a wrong out-spec before XLA materializes an "
    "accidental all-gather",
    path_matcher("dgraph_tpu/"),
    scope="dgraph_tpu/",
)
def check_unchecked_shard_map(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(node.func) != "shard_map":
            continue
        for kw in node.keywords:
            if kw.arg in ("check_vma", "check_rep"):
                findings.append(Finding(
                    "no-unchecked-shard-map", relpath, kw.value.lineno,
                    f"raw {kw.arg}= kwarg at a shard_map call site: route "
                    f"check kwargs through comm.collectives."
                    f"shard_map_checks(...) so relaxing the replication "
                    f"checker stays one greppable, reasoned decision",
                ))
            elif kw.arg is None:  # **splat
                v = kw.value
                if (
                    isinstance(v, ast.Call)
                    and _last_segment(v.func) == "shard_map_checks"
                ):
                    continue
                findings.append(Finding(
                    "no-unchecked-shard-map", relpath, v.lineno,
                    f"shard_map kwargs splatted from "
                    f"{_dotted(v) or ast.dump(v)[:40]!r} — only "
                    f"**shard_map_checks(...) may carry check kwargs into "
                    f"a shard_map call",
                ))
    return findings


# ---------------------------------------------------------------------------
# no-nondeterminism-in-plan
# ---------------------------------------------------------------------------

SEEDED_RNG_CONSTRUCTORS = frozenset({
    "default_rng", "Generator", "RandomState", "SeedSequence", "Random",
    "PRNGKey", "key",
})
WALL_CLOCK_CALLS = frozenset({
    "time", "time_ns", "perf_counter", "perf_counter_ns", "monotonic", "now",
    "utcnow", "today",
})


@rule(
    "no-nondeterminism-in-plan",
    "plan/partition builds must be deterministic in (graph, seed): no "
    "unseeded RNG and no wall-clock reads (plans are content-addressed "
    "into the cache and signed by the tuner)",
    path_matcher(
        "dgraph_tpu/plan.py", "dgraph_tpu/partition.py",
        "dgraph_tpu/tune/signature.py",
    ),
    scope="plan.py, partition.py, tune/signature.py",
)
def check_plan_determinism(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        last = dotted.rsplit(".", 1)[-1] if dotted else ""
        if ".random." in f".{dotted}" or dotted.startswith("random."):
            if last in SEEDED_RNG_CONSTRUCTORS:
                if not node.args and not node.keywords:
                    findings.append(Finding(
                        "no-nondeterminism-in-plan", relpath, node.lineno,
                        f"'{dotted}()' with no seed in a plan-build path",
                    ))
            else:
                findings.append(Finding(
                    "no-nondeterminism-in-plan", relpath, node.lineno,
                    f"unseeded module-level RNG call '{dotted}' in a "
                    f"plan-build path (use a seeded default_rng)",
                ))
        elif (
            last in WALL_CLOCK_CALLS
            and dotted.split(".", 1)[0] in ("time", "datetime", "dt")
        ):
            findings.append(Finding(
                "no-nondeterminism-in-plan", relpath, node.lineno,
                f"wall-clock read '{dotted}' in a plan-build path",
            ))
    return findings


# ---------------------------------------------------------------------------
# no-monolithic-plan-pickle
# ---------------------------------------------------------------------------

PLAN_BUILDERS = frozenset({
    "build_edge_plan", "build_edge_plan_sharded", "cached_edge_plan",
    "_finalize_plan", "assemble_plan", "load_sharded_plan",
})


def _mentions_plan(expr: ast.AST) -> Optional[str]:
    """The identifier that makes ``expr`` plan-shaped (a name/attribute
    containing 'plan', or a direct plan-builder call), else None."""
    for node in ast.walk(expr):
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Call):
            name = _last_segment(node.func)
            if name in PLAN_BUILDERS:
                return name
        if name and "plan" in name.lower():
            return name
    return None


@rule(
    "no-monolithic-plan-pickle",
    "no atomic_pickle_dump of a whole EdgePlan outside the shard writer "
    "(plan_shards.py): the monolithic plan pickle is the ~40+ GB "
    "all-or-nothing artifact that OOM-killed the papers100M build — plans "
    "persist as per-rank shards + a checksummed manifest (cache format v8)",
    lambda relpath: (
        relpath.startswith("dgraph_tpu/")
        and relpath != "dgraph_tpu/plan_shards.py"
    ),
    scope="dgraph_tpu/ except plan_shards.py",
)
def check_monolithic_plan_pickle(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_segment(node.func) != "atomic_pickle_dump":
            continue
        payloads = list(node.args[1:]) + [k.value for k in node.keywords]
        for payload in payloads:
            why = _mentions_plan(payload)
            if why:
                findings.append(Finding(
                    "no-monolithic-plan-pickle", relpath, node.lineno,
                    f"atomic_pickle_dump of plan-shaped payload ({why!r}) "
                    f"outside the shard writer: persist plans as per-rank "
                    f"shards + manifest (plan_shards.PlanShardWriter / "
                    f"plan.build_plan_shards), not one monolithic pickle",
                ))
                break
    return findings


# ---------------------------------------------------------------------------
# no-unpriced-wire-cast
# ---------------------------------------------------------------------------

# dtypes narrower than fp32 whose literal spelling in a cast marks a
# deliberate narrowing (a cast to ``x.dtype`` / a widening to f32 never
# matches)
NARROW_DTYPES = frozenset({
    "bfloat16", "float16", "float8_e4m3fn", "float8_e5m2", "int8", "uint8",
})
# calls that put an operand on the wire: the lax collectives plus the
# pallas p2p transport entry point
WIRE_EXCHANGE_CALLS = COLLECTIVE_CALLS | frozenset({"p2p_transport"})


def _narrow_dtype_literal(node) -> Optional[str]:
    """The narrow dtype a cast argument names literally, else None."""
    if isinstance(node, ast.Constant) and node.value in NARROW_DTYPES:
        return str(node.value)
    if isinstance(node, ast.Attribute) and node.attr in NARROW_DTYPES:
        return node.attr
    if isinstance(node, ast.Name) and node.id in NARROW_DTYPES:
        return node.id
    return None


@rule(
    "no-unpriced-wire-cast",
    "no literal dtype-narrowing astype/convert_element_type in a function "
    "that puts operands on the wire (issues a lax collective or the p2p "
    "transport): an ad-hoc cast ships bytes the footprint model, trace/HLO "
    "auditors and tuner never price — narrowing wire payloads is "
    "dgraph_tpu.wire's job (encode/decode pairs, priced end to end)",
    path_matcher("dgraph_tpu/comm/", "dgraph_tpu/ops/"),
    scope="comm/, ops/",
)
def check_unpriced_wire_cast(relpath: str, tree: ast.AST, lines: list):
    findings = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        issues = [
            sub.lineno for sub in ast.walk(fn)
            if isinstance(sub, ast.Call)
            and _last_segment(sub.func) in WIRE_EXCHANGE_CALLS
        ]
        if not issues:
            continue
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            last = _last_segment(sub.func)
            arg = None
            if last == "astype" and sub.args:
                arg = sub.args[0]
            elif last == "convert_element_type":
                cands = list(sub.args[1:]) + [
                    k.value for k in sub.keywords if k.arg == "new_dtype"
                ]
                arg = cands[0] if cands else None
            dt = _narrow_dtype_literal(arg) if arg is not None else None
            if dt:
                findings.append(Finding(
                    "no-unpriced-wire-cast", relpath, sub.lineno,
                    f"literal narrowing cast to {dt!r} inside {fn.name!r} "
                    f"(line {fn.lineno}), which puts operands on the wire "
                    f"(exchange call at line {issues[0]}): those bytes are "
                    f"invisible to footprint/trace/tuner — route narrowing "
                    f"through dgraph_tpu.wire (make_wire_transform / "
                    f"make_*_codec) so the encoded payload is priced and "
                    f"verified end to end",
                ))
    return findings


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def repo_root() -> str:
    """The directory containing the ``dgraph_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_source_files(root: str):
    pkg = os.path.join(root, "dgraph_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_file(path: str, root: str, rules=None) -> list:
    """Run every applicable rule over one file; returns unsuppressed
    findings."""
    relpath = os.path.relpath(path, root).replace(os.sep, "/")
    source = open(path).read()
    lines = source.splitlines()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("syntax", relpath, e.lineno or 0, f"unparseable: {e}")]
    findings = []
    for r in (rules or RULES).values():
        if not r.applies(relpath):
            continue
        if r.name == "jax-free-module":
            got = r.check(relpath, tree, lines, root=root)
        else:
            got = r.check(relpath, tree, lines)
        findings.extend(
            f for f in got if not _suppressed(lines, f.line, f.rule)
        )
    return findings


def run_lint(root: Optional[str] = None, rules=None) -> dict:
    """Lint the whole ``dgraph_tpu`` tree; returns a JSON-able report."""
    root = root or repo_root()
    findings, n_files = [], 0
    for path in iter_source_files(root):
        n_files += 1
        findings.extend(lint_file(path, root, rules))
    findings.sort(key=lambda f: (f.path, f.line))
    per_rule: dict = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return {
        "kind": "lint_report",
        "root": root,
        "files_checked": n_files,
        "rules": sorted(RULES),
        "findings": [f.to_dict() for f in findings],
        "per_rule": per_rule,
        "ok": not findings,
    }
