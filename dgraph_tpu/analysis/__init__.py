"""Static analysis: trace auditing + contract linting.

Five PRs in, the repo's hardest-won invariants existed only by convention:
the tuner auto-adopts configs priced by ``obs.footprint``'s analytic
schedule with nothing checking that the traced program actually emits that
schedule; PR 4 removed a mixed-lowering hazard (a config re-read inside a
traced function could hand the forward exchange and its transpose different
lowerings) that nothing prevented from regressing; and ``chaos`` /
``train.supervise`` / the standalone health loader stayed jax-free only by
hand-enforced discipline.  This package is the machine-checked backstop —
the analogue of DGraph's layered Communicator design (each layer's contract
checkable in isolation) and of "Memory-efficient array redistribution"
(PAPERS.md), which treats the emitted collective schedule as a verifiable
artifact rather than a hope:

- :mod:`dgraph_tpu.analysis.trace` — the **trace auditor**: abstractly
  traces (``jax.make_jaxpr`` / ``jax.eval_shape`` — zero XLA compiles) the
  train step, eval step, and serve bucket forward under each halo lowering
  and verifies the traced collective schedule against the one
  ``obs.footprint`` priced (op counts AND operand bytes — the numbers the
  tuner ranks on), plus single-lowering-per-program, no host callbacks,
  fp32 accumulation, and donation consumption.
- :mod:`dgraph_tpu.analysis.hlo` — the **lowered-artifact auditor**
  (ISSUE 12): one tier below the jaxpr, ``jit(...).lower()`` (StableHLO —
  never ``.compile()``) for every (program, halo lowering) pair and
  verifies the post-lowering schedule: collective kinds/counts/
  replica_groups vs the plan, operand bytes vs ``obs.footprint``, **no
  XLA-materialized collective the plan didn't schedule** (the accidental
  all-gather class the ``pallas_p2p`` relaxed replication checker can no
  longer catch), one transport family per program, and
  ``(params, opt_state)`` donation surviving lowering as donor/alias
  entries.
- :mod:`dgraph_tpu.analysis.kernel` — the **Pallas DMA-discipline
  verifier**: static rules over the ``pallas_p2p`` transport kernel's
  jaxpr (every ``dma_start`` paired with send+recv waits, nothing
  outstanding at exit, wait-before-reuse on the double-buffer slots,
  VMEM staging within the fused-mask budget, destination rows provably
  ``[me*S, (me+1)*S)``).
- :mod:`dgraph_tpu.analysis.spmd` — the **cross-rank SPMD divergence
  auditor** (ISSUE 13): every rank's train/eval/serve program lowered
  from that rank's plan-shard subset view under that rank's env, then
  proven identical — canonicalized module bytes, the program-order
  collective issue sequence (the deadlock detector: the NCCL/NVSHMEM
  class hangs, not errors, on schedule mismatch), per-rank live-delta
  symmetry, and tuned-record resolution agreement — across 2/4-shard
  worlds and both generations of a ``train/shrink.py`` transition.
- :mod:`dgraph_tpu.analysis.lint` — the **contract linter**: stdlib-``ast``
  rules over the source tree (jax-free modules, no config reads in traced
  bodies — pallas kernel bodies included, custom_vjp pairing, named_scope
  on collectives, shard_map check kwargs routed through
  ``shard_map_checks``, deterministic plan builds), with a small registry
  so new contracts are one rule away.

CLI::

    python -m dgraph_tpu.analysis              # lint + audit (all tiers)
    python -m dgraph_tpu.analysis --selftest   # compile-free tier-1 smoke

- :mod:`dgraph_tpu.analysis.host` — the **host-side concurrency &
  durability auditor** (the fifth tier, and the only one that audits the
  *host* program instead of the device program): stdlib-``ast`` race /
  deadlock / torn-write rules over the jax-free control plane — per-class
  guarded-field inference with out-of-lock access flagging (thread-escape
  aware), the inter-class lock-acquisition-order graph (cycles RED), the
  atomic-writer routing for durable artifacts, the pointer-flip-last CFG
  check on generation commits, and the bidirectional chaos-registry
  coverage drift check.

This module deliberately imports neither jax nor numpy at module level:
``lint`` and ``host`` are pure stdlib, and ``trace`` pulls jax in lazily
so the CLI can pin the platform/device-count env before any backend
decision is made.  Importing the package registers the host rules in
``lint.RULES`` (one registry: ``--list_rules``, the docs-catalog pin and
the ``# lint: allow(...)`` pragma cover all five tiers' rules).
"""

from __future__ import annotations

from dgraph_tpu.analysis import host  # noqa: F401  (registers host rules)

__all__ = ["hlo", "host", "kernel", "lint", "spmd", "trace"]
