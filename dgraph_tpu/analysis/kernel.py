"""Pallas DMA-discipline verifier: static checks over the ``pallas_p2p``
transport kernel's jaxpr.

"Demystifying NVSHMEM" (PAPERS.md) makes the point this module encodes:
device-initiated one-sided communication is only correct under an exact
semaphore/ordering discipline, and that discipline is *invisible* to
every numeric test — Pallas interpret mode executes shards lock-step, so
a dropped wait or a premature staging-slot overwrite produces bit-perfect
CPU parity and corrupts halos only on real hardware under real timing.
The discipline is, however, fully *static*: the transport kernel is a
straight-line jaxpr whose DMA starts, waits, semaphore indices and
staging-slot indices are all literal, so every rule below is checkable
with zero chips and zero XLA compiles (``jax.make_jaxpr`` only).

Per transport ``pallas_call`` the verifier proves:

- **paired waits** — every ``dma_start``'s send semaphore AND recv
  semaphore is waited by a later ``dma_wait`` on the same
  (semaphore, index);
- **nothing outstanding at exit** — per (semaphore, index), waits cover
  starts by the last eqn (an un-drained DMA at kernel exit is a race
  against the next kernel's buffer reuse);
- **wait-before-reuse** — a write to a staging slot that an earlier put
  read must be preceded by that put's send-semaphore wait (the classic
  double-buffer hazard: overwriting bytes still on the wire);
- **VMEM discipline** — the fused-mask variant stages through exactly two
  tile-sized VMEM slots and only engages when the send stack fits
  ``ops.pallas_p2p.FUSED_MASK_VMEM_BUDGET``; the pre-masked variant
  carries no dead staging;
- **destination rows provably local** — every remote put lands in
  ``out_ref[ds(start, S)]`` where ``start`` is loaded from the meta
  scalar the host computes as ``axis_index * S`` (checked by producer
  chase in the ENCLOSING jaxpr), so the landing rows are exactly
  ``[me*S, (me+1)*S)`` — the plan's halo-slot numbering, never another
  shard's rows.

``python -m dgraph_tpu.analysis.kernel --selftest true`` runs the
vacuity guards: deliberately broken kernel variants (dropped send wait,
dropped recv wait, slot reuse without wait, wrong dst-row slot, oversized
staging) must each go RED while the real transport stays GREEN.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from dgraph_tpu.analysis.trace import walk_eqns  # noqa: F401  (re-export)

__all__ = [
    "collect_transports",
    "verify_transport",
    "audit_workload_kernels",
    "kernel_selftest_failures",
]


def _aval_space(aval) -> str:
    """Best-effort memory-space tag of a pallas MemRef aval ('vmem',
    'smem', 'semaphore', 'any', or '?' for plain arrays)."""
    s = str(aval)
    for tag in ("semaphore", "vmem", "smem", "any"):
        if f"<{tag}" in s or f"{tag}_mem" in s:
            return "semaphore" if tag == "semaphore" else tag
    return "?"


def _walk_with_parent(jaxpr, visit) -> None:
    """Like :func:`~dgraph_tpu.analysis.trace.walk_eqns` but hands the
    ENCLOSING jaxpr to ``visit(eqn, parent)`` — the kernel verifier needs
    it to chase a pallas_call operand back to its producer."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        visit(eqn, jaxpr)
        for p in eqn.params.values():
            for item in p if isinstance(p, (list, tuple)) else [p]:
                inner = getattr(item, "jaxpr", None)
                if inner is not None:
                    _walk_with_parent(getattr(inner, "jaxpr", inner), visit)
                elif hasattr(item, "eqns"):
                    _walk_with_parent(item, visit)


def collect_transports(closed_jaxpr) -> list:
    """Every ``pallas_call`` eqn carrying at least one remote DMA, paired
    with its enclosing jaxpr: ``[(eqn, parent_jaxpr), ...]``."""
    from dgraph_tpu.analysis.trace import _remote_put_count

    out = []

    def visit(eqn, parent):
        if eqn.primitive.name != "pallas_call":
            return
        inner = eqn.params.get("jaxpr")
        if inner is None:
            return
        if _remote_put_count(getattr(inner, "jaxpr", inner)):
            out.append((eqn, parent))

    _walk_with_parent(closed_jaxpr, visit)
    return out


# ---------------------------------------------------------------------------
# jaxpr decoding helpers
# ---------------------------------------------------------------------------


def _literal_val(x) -> Optional[int]:
    try:
        from jax._src.core import Literal
    except ImportError:  # pragma: no cover - jax layout drift
        from jax.core import Literal

    if isinstance(x, Literal):
        try:
            return int(x.val)
        except (TypeError, ValueError):
            return None
    if isinstance(x, int):
        return int(x)
    return None


def _indexer_key(transforms) -> tuple:
    """Hashable identity of a ref's indexing transforms: literal index
    values and slice (start, size) pairs, with dynamic starts reduced to
    the producing var's id (so the same loaded scalar matches)."""
    import jax

    out = []
    for idxr in transforms or ():
        for idx in getattr(idxr, "indices", ()) or ():
            if isinstance(idx, jax.core.Literal):
                out.append(("lit", _literal_val(idx)))
            elif hasattr(idx, "start"):  # Slice
                start = idx.start
                lit = _literal_val(start)
                out.append((
                    "slice",
                    lit if lit is not None else f"var{id(start)}",
                    getattr(idx, "size", None),
                ))
            elif isinstance(idx, int):
                out.append(("lit", idx))
            else:
                out.append(("var", id(idx)))
    return tuple(out)


def _first_slice(transforms):
    """The leading (start, size) of a ref's first indexer — the landing
    row window of a DMA destination."""
    for idxr in transforms or ():
        for idx in getattr(idxr, "indices", ()) or ():
            if hasattr(idx, "start") and hasattr(idx, "size"):
                return idx.start, int(idx.size)
            lit = _literal_val(idx)
            if lit is not None:
                return lit, 1
    return None, None


@dataclasses.dataclass
class _Dma:
    pos: int
    src: object
    src_t: object
    dst: object
    dst_t: object
    send_key: tuple  # (id(sem var), indexer key)
    recv_key: tuple
    remote: bool
    dst_start: object
    dst_size: Optional[int]


def _decode_dma(eqn, pos: int) -> _Dma:
    from jax import tree_util as jtu

    (src, src_t, dst, dst_t, dst_sem, dst_sem_t, src_sem, src_sem_t,
     device_id) = jtu.tree_unflatten(eqn.params["tree"], eqn.invars)
    start, size = _first_slice(dst_t)
    return _Dma(
        pos=pos, src=src, src_t=src_t, dst=dst, dst_t=dst_t,
        send_key=(id(src_sem), _indexer_key(src_sem_t))
        if src_sem is not None else None,
        recv_key=(id(dst_sem), _indexer_key(dst_sem_t))
        if dst_sem is not None else None,
        remote=device_id is not None,
        dst_start=start, dst_size=size,
    )


def _chase(producers: dict, var, through=("convert_element_type", "reshape",
                                          "broadcast_in_dim", "squeeze",
                                          "expand_dims")):
    """Follow single-operand pass-through eqns back to the interesting
    producer of ``var`` (or None for a jaxpr invar/constvar)."""
    seen = 0
    while var in producers and seen < 32:
        eqn = producers[var]
        if eqn.primitive.name not in through:
            return eqn
        var = eqn.invars[0]
        seen += 1
    return None


def _producer_map(jaxpr) -> dict:
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


# ---------------------------------------------------------------------------
# the verifier
# ---------------------------------------------------------------------------


def verify_transport(call_eqn, parent_jaxpr, label: str, failures: list,
                     budget: Optional[int] = None) -> dict:
    """Statically verify ONE transport pallas_call's DMA discipline;
    returns the per-kernel record and appends human-readable failures."""
    import numpy as np

    from dgraph_tpu.ops.pallas_p2p import FUSED_MASK_VMEM_BUDGET

    budget = FUSED_MASK_VMEM_BUDGET if budget is None else budget
    kj = call_eqn.params["jaxpr"]
    kj = getattr(kj, "jaxpr", kj)

    def fail(msg):
        failures.append(f"[kernel:{label}] {msg}")

    # --- kernel operand layout (meta | mask | blocks | zeros | out |
    # staging | send_sems | recv_sems) -------------------------------------
    invars = list(kj.invars)
    if len(invars) != 8:
        fail(
            f"unrecognized transport kernel layout: {len(invars)} operands "
            f"(expected meta/mask/blocks/zeros/out + staging/send/recv "
            f"sems) — update analysis.kernel alongside ops.pallas_p2p"
        )
        return {"label": label, "ok": False}
    meta, mask, blocks, zeros, out_ref, staging, send_sems, recv_sems = invars
    meta_len = int(meta.aval.shape[0])
    n = (meta_len - 1) // 3
    if 3 * n + 1 != meta_len or n < 1:
        fail(f"meta operand length {meta_len} is not 3n+1")
        return {"label": label, "ok": False}
    blocks_shape = tuple(int(s) for s in blocks.aval.shape)
    S, F = blocks_shape[1], blocks_shape[2]
    itemsize = np.dtype(blocks.aval.dtype).itemsize
    fused = tuple(int(s) for s in mask.aval.shape) != (1, 1)
    out_rows = int(out_ref.aval.shape[0])

    # --- VMEM discipline ---------------------------------------------------
    staging_shape = tuple(int(s) for s in staging.aval.shape)
    tile_bytes = S * F * itemsize
    stack_bytes = n * tile_bytes
    if fused:
        if _aval_space(blocks.aval) != "vmem":
            fail("fused-mask kernel does not stage its send stack in VMEM")
        if stack_bytes > budget:
            fail(
                f"fused-mask send stack is {stack_bytes} B in VMEM; the "
                f"budget is {budget} B — this stack must fall back to "
                f"pre-masked HBM-direct puts"
            )
        if staging_shape != (2, S, F):
            import math

            fail(
                f"staging buffer is {staging_shape}; the double-buffer "
                f"contract is exactly two [S={S}, F={F}] slots "
                f"({2 * tile_bytes} B), not "
                f"{math.prod(staging_shape) * itemsize} B"
            )
    else:
        if staging_shape not in ((1, 1),):
            fail(
                f"pre-masked kernel carries a {staging_shape} staging "
                f"buffer — dead VMEM on the path that exists to avoid it"
            )

    # --- classify eqns in order --------------------------------------------
    starts: list = []
    waits: list = []  # (pos, waited key)
    slot_writes: list = []  # (pos, slot literal)
    meta_loads: dict = {}  # outvar -> literal index into meta
    for pos, eqn in enumerate(kj.eqns):
        name = eqn.primitive.name
        if name == "dma_start":
            starts.append(_decode_dma(eqn, pos))
        elif name == "dma_wait":
            d = _decode_dma(eqn, pos)
            # dma_wait waits the semaphore in its dst slot (wait_send
            # swaps src/dst so the send semaphore lands there)
            waits.append((pos, d.recv_key))
        elif name in ("swap", "addupdate") and eqn.invars and eqn.invars[0] is staging:
            # swap binds (ref, val, *transform_leaves); the staging write's
            # only dynamic-or-literal transform leaf is the slot index
            slot = None
            for v in eqn.invars[2:]:
                slot = _literal_val(v)
                if slot is not None:
                    break
            slot_writes.append((pos, slot))
        elif name == "get" and eqn.invars and eqn.invars[0] is meta:
            idx = None
            for v in eqn.invars[1:]:
                idx = _literal_val(v)
                if idx is not None:
                    break
            for ov in eqn.outvars:
                meta_loads[ov] = idx

    remote = [d for d in starts if d.remote]
    if not remote:
        fail("transport kernel issues no remote dma_start at all")

    # --- paired waits + nothing outstanding --------------------------------
    for d in starts:
        for key, which in ((d.send_key, "send"), (d.recv_key, "recv")):
            if key is None:
                if which == "send" and d.remote:
                    fail(f"remote dma_start at eqn {d.pos} has no send "
                         f"semaphore")
                continue
            if not any(w_pos > d.pos and w_key == key
                       for w_pos, w_key in waits):
                fail(
                    f"dma_start at eqn {d.pos} has no later dma_wait on its "
                    f"{which} semaphore — the transfer is unsynchronized"
                )
    per_key_starts: dict = {}
    for d in starts:
        for key in (d.send_key, d.recv_key):
            if key is not None:
                per_key_starts[key] = per_key_starts.get(key, 0) + 1
    per_key_waits: dict = {}
    for _pos, key in waits:
        per_key_waits[key] = per_key_waits.get(key, 0) + 1
    for key, n_started in per_key_starts.items():
        if per_key_waits.get(key, 0) < n_started:
            fail(
                f"semaphore {key[1]} outstanding at kernel exit: "
                f"{n_started} start(s), {per_key_waits.get(key, 0)} wait(s)"
            )

    # --- wait-before-reuse (double-buffer slot discipline) ------------------
    for w_pos, slot in slot_writes:
        for d in starts:
            if d.pos >= w_pos or d.src is not staging:
                continue
            d_slot = None
            for entry in _indexer_key(d.src_t):
                if entry[0] == "lit":
                    d_slot = entry[1]
                    break
            if d_slot != slot:
                continue
            waited = any(
                d.pos < p < w_pos and key == d.send_key
                for p, key in waits
            )
            if not waited:
                fail(
                    f"staging slot {slot} rewritten at eqn {w_pos} while "
                    f"the put started at eqn {d.pos} may still be reading "
                    f"it — wait the send semaphore before slot reuse"
                )

    # --- destination rows provably [me*S, (me+1)*S) -------------------------
    dst_slot_idx = 3 * n  # meta layout: targets[n] | sources[n] | ranks[n] | me*S
    for d in remote:
        if d.dst is not out_ref:
            fail(f"remote put at eqn {d.pos} does not target the halo "
                 f"output buffer")
            continue
        if d.dst_size != S:
            fail(
                f"remote put at eqn {d.pos} lands {d.dst_size} rows; the "
                f"halo slot is exactly S={S} rows"
            )
        start = d.dst_start
        lit = _literal_val(start)
        if lit is not None:
            fail(
                f"remote put at eqn {d.pos} lands at constant row {lit}, "
                f"not this shard's me*S halo slot"
            )
            continue
        if meta_loads.get(start, -1) != dst_slot_idx:
            fail(
                f"remote put at eqn {d.pos}: destination row is not loaded "
                f"from meta[{dst_slot_idx}] (the me*S slot) — landing rows "
                f"are not provably inside [me*S, (me+1)*S)"
            )
    if out_rows % S != 0:
        fail(f"halo buffer rows {out_rows} not a multiple of S={S}")

    # --- enclosing-jaxpr provenance: meta[3n] == axis_index * S -------------
    producers = _producer_map(parent_jaxpr)
    meta_src = _chase(producers, call_eqn.invars[0])
    ok_meta = False
    if meta_src is not None and meta_src.primitive.name == "concatenate":
        tail = meta_src.invars[-1]
        mul = _chase(producers, tail)
        if mul is not None and mul.primitive.name == "mul":
            lit = [_literal_val(v) for v in mul.invars]
            axis_ops = [
                _chase(producers, v) for v in mul.invars
                if _literal_val(v) is None
            ]
            ok_meta = (
                S in lit
                and any(
                    e is not None and e.primitive.name == "axis_index"
                    for e in axis_ops
                )
            )
    if remote and not ok_meta:
        fail(
            f"meta[{dst_slot_idx}] is not computed as axis_index * S in the "
            f"enclosing program — cannot prove the puts land in this "
            f"shard's own halo rows"
        )

    return {
        "label": label,
        "n_deltas": n,
        "s_pad": S,
        "feat_dim": F,
        "fused_mask": fused,
        "num_dma_starts": len(starts),
        "num_remote_puts": len(remote),
        "num_dma_waits": len(waits),
        "num_slot_writes": len(slot_writes),
        "stack_bytes": stack_bytes,
        "ok": True,
    }


# ---------------------------------------------------------------------------
# workload-level audit (the real transports, as the models trace them)
# ---------------------------------------------------------------------------


def audit_workload_kernels(w, programs=None) -> dict:
    """Pin ``pallas_p2p``, trace every registered program abstractly, and
    verify each transport kernel's DMA discipline. Returns a
    ``kind="kernel_audit"`` report (``ok``/``failures`` caller contract
    like the other audit tiers)."""
    import jax

    from dgraph_tpu import config as _cfg
    from dgraph_tpu.analysis.trace import PROGRAMS

    failures: list = []
    kernels = []
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl, _cfg.use_pallas_p2p)
    try:
        _cfg.set_flags(
            halo_impl="pallas_p2p", tuned_halo_impl=None, use_pallas_p2p=True
        )
        for label, build in (programs or PROGRAMS).items():
            fn, args = build(w)
            jaxpr = jax.make_jaxpr(fn)(*args)
            transports = collect_transports(jaxpr)
            if not transports:
                failures.append(
                    f"[kernel:{label}] pallas_p2p pinned but the program "
                    f"traced no transport kernels"
                )
            for i, (eqn, parent) in enumerate(transports):
                kernels.append(
                    verify_transport(eqn, parent, f"{label}#{i}", failures)
                )
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            use_pallas_p2p=saved[2],
        )
    return {
        "kind": "kernel_audit",
        "world_size": w.world_size,
        "num_halo_deltas": len(w.plan_np.halo_deltas),
        "kernels": kernels,
        "failures": failures,
        "ok": not failures,
    }


# ---------------------------------------------------------------------------
# vacuity guards: broken kernels the verifier MUST flag
# ---------------------------------------------------------------------------


def _mutant_jaxpr(W: int, S: int, F: int, deltas: tuple, mutation: Optional[str]):
    """Trace a transport-shaped kernel with one seeded discipline bug
    (``mutation`` in {None, 'drop_send_wait', 'drop_recv_wait',
    'no_slot_wait', 'bad_dst_row', 'oversize_staging'}) under shard_map —
    ``jax.make_jaxpr`` only, zero compiles."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.compat import install_multiaxis_remote_dma
    from dgraph_tpu.ops.pallas_p2p import _logical_device_ids

    install_multiaxis_remote_dma()
    n = len(deltas)
    slots = 4 if mutation == "oversize_staging" else 2

    def kern(meta_ref, mask_ref, blocks_ref, zeros_ref, out_ref, staging,
             send_sems, recv_sems):
        del zeros_ref
        dst_idx = 2 * n if mutation == "bad_dst_row" else 3 * n
        dst_row = meta_ref[dst_idx]
        copies = []
        for k in range(n):
            slot = k % slots
            if k >= slots and mutation != "no_slot_wait":
                copies[k - slots].wait_send()
            staging[slot] = blocks_ref[k] * mask_ref[k][:, None].astype(
                blocks_ref.dtype
            )
            c = pltpu.make_async_remote_copy(
                src_ref=staging.at[slot],
                dst_ref=out_ref.at[pl.ds(dst_row, S)],
                send_sem=send_sems.at[k],
                recv_sem=recv_sems.at[k],
                device_id=meta_ref[k],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            copies.append(c)
        if mutation == "no_slot_wait":
            # drain EVERY send here so only the reuse ORDERING is wrong
            # (the paired-wait rule stays satisfied; rule C alone fires)
            drain = copies
        else:
            # the slot-reuse waits above consumed all but the last
            # ``slots`` sends — drain those, minus the seeded drop
            drain = copies[-slots:]
            if mutation == "drop_send_wait":
                drain = drain[:-1]
        for c in drain:
            c.wait_send()
        for k in range(n):
            if mutation == "drop_recv_wait" and k == n - 1:
                continue
            src_row = meta_ref[2 * n + k] * S
            landing = out_ref.at[pl.ds(src_row, S)]
            pltpu.make_async_copy(landing, landing, recv_sems.at[k]).wait()

    ANY = pltpu.TPUMemorySpace.ANY
    call = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((W * S, F), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.VMEM),
            pl.BlockSpec(memory_space=ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=ANY),
        scratch_shapes=[
            pltpu.VMEM((slots, S, F), jnp.float32),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        input_output_aliases={3: 0},
        interpret=True,
        name=f"dgraph_p2p_mutant_{mutation or 'clean'}",
    )

    def body(blocks, mask):
        me = lax.axis_index("x")
        d = jnp.asarray(deltas, jnp.int32)
        targets = (me + d) % W
        sources = (me - d) % W
        meta = jnp.concatenate([
            _logical_device_ids("x", targets),
            _logical_device_ids("x", sources),
            sources,
            (me * S)[None],
        ]).astype(jnp.int32)
        zeros = jnp.zeros((W * S, F), jnp.float32)
        return call(meta, mask, blocks, zeros)

    mesh = jax.make_mesh((W,), ("x",))
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("x"), P("x")),
        out_specs=P("x"),
        **shard_map_checks(impl="pallas_p2p"),
    )
    blocks = jax.ShapeDtypeStruct((W * n, S, F), np.float32)
    mask = jax.ShapeDtypeStruct((W * n, S), np.float32)
    return jax.make_jaxpr(fn)(blocks, mask)


def kernel_selftest_failures(W: int = 4, S: int = 8, F: int = 16) -> list:
    """Vacuity guards for the DMA verifier: the clean kernel must verify
    GREEN and every seeded discipline mutation must go RED. Needs W >= 4
    so three live deltas exercise the slot-reuse path."""
    deltas = tuple(range(1, min(W, 4)))
    failures: list = []

    def run(mutation):
        jaxpr = _mutant_jaxpr(W, S, F, deltas, mutation)
        transports = collect_transports(jaxpr)
        if len(transports) != 1:
            return [f"expected 1 transport, traced {len(transports)}"]
        mism: list = []
        verify_transport(*transports[0], f"mutant:{mutation}", mism)
        return mism

    clean = run(None)
    if clean:
        failures.append(
            f"verifier flagged the CLEAN transport kernel: {clean[:3]}"
        )
    for mutation, hint in (
        ("drop_send_wait", "send semaphore"),
        ("drop_recv_wait", "recv semaphore"),
        ("no_slot_wait", "slot"),
        ("bad_dst_row", "meta["),
        ("oversize_staging", "staging"),
    ):
        mism = run(mutation)
        if not mism:
            failures.append(
                f"verifier accepted the {mutation!r} mutant — the "
                f"{hint} rule is vacuous"
            )
    return failures


def main(cfg) -> dict:
    import json

    from dgraph_tpu.obs.health import RunHealth

    health = RunHealth.begin("analysis.kernel")
    try:
        failures: list = []
        report = None
        if cfg.selftest:
            failures.extend(kernel_selftest_failures())
        if cfg.audit:
            from dgraph_tpu.analysis.trace import build_audit_workload

            w = build_audit_workload(cfg.world, seed=cfg.seed)
            report = audit_workload_kernels(w)
            failures.extend(report["failures"])
        out = {
            "kind": "kernel_verifier",
            "failures": failures,
            "audit": {
                "kernels": len(report["kernels"]),
                "ok": report["ok"],
            } if report else None,
            "run_health": health.finish(
                "; ".join(failures) if failures else None,
                wedge="stage_failure" if failures else None,
            ),
        }
        print(json.dumps(out, indent=cfg.indent or None))
        if failures:
            raise SystemExit(
                "kernel verifier FAILED: " + "; ".join(failures[:8])
            )
        return out
    except SystemExit:
        raise
    except BaseException as e:
        print(json.dumps({
            "kind": "kernel_verifier",
            "failures": [f"{type(e).__name__}: {e}"],
            "run_health": health.finish(
                f"kernel verifier crashed: {type(e).__name__}: {e}",
                wedge="stage_failure",
            ),
        }))
        raise


if __name__ == "__main__":
    import os

    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from dgraph_tpu.utils.cli import parse_config

    @dataclasses.dataclass
    class Config:
        """Pallas DMA-discipline verifier (``--selftest`` runs the broken-
        kernel vacuity guards; ``--audit`` verifies the real transports)."""

        selftest: bool = False
        audit: bool = True
        world: int = 2
        seed: int = 0
        indent: int = 0

    main(parse_config(Config))
