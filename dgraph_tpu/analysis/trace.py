"""Trace auditor: verify the traced collective schedule against the one
``obs.footprint`` priced.

The tuner auto-adopts configs ranked by :func:`dgraph_tpu.obs.footprint.
plan_footprint`'s analytic schedule — collective op counts and operand byte
volumes computed on host from the plan alone.  Nothing, until this module,
checked that the program jax actually traces emits *that* schedule: a
lowering regression (a stray all_to_all on the ppermute path, a halo
exchange that silently upcast its operand, a second collective sneaking
into one leg) would leave the tuner ranking fiction.  "Memory-efficient
array redistribution" (PAPERS.md) treats the emitted collective schedule as
a verifiable artifact; this is that check for dgraph_tpu.

Everything here is ABSTRACT: programs are traced with ``jax.make_jaxpr`` /
``jax.eval_shape`` over ``ShapeDtypeStruct``/numpy operands — zero XLA
compiles, zero device buffers, so the audit runs in tier-1 and in the
bench's no-healthy-chip fallback at interactive speed.

Per (program, halo lowering) the auditor verifies:

- **schedule**: collective op counts and per-operand bytes match
  ``plan_footprint`` at the traced feature width/dtype (``all_to_all``
  operands == the padded ``[W, S, F]`` block; each ``ppermute`` round ==
  one ``[S, F]`` block; round count == ``legs * num_halo_deltas`` where
  ``legs`` is measured from the all_to_all-pinned trace of the same
  program);
- **single lowering**: exactly one halo-lowering family per traced
  program — the PR 4 mixed-lowering hazard, machine-checked;
- **no host callbacks** inside traced code;
- **fp32 accumulation**: no ``psum``-family collective runs on a
  sub-32-bit dtype (bf16 may ride the wire; reductions must not);
- **donation**: every donated buffer's (shape, dtype) is matched by an
  output — otherwise the donation is silently dropped and peak HBM grows
  by the full params+opt_state footprint.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Optional

HALO_IMPLS = ("all_to_all", "ppermute", "overlap", "pallas_p2p", "sched")

# psum family across jax versions: 'psum' (0.6+), 'psum2'/'pbroadcast'
# (0.4.x shard_map rewrite); pmean lowers through psum
PSUM_PRIMS = ("psum", "psum2", "psum_invariant", "pmean")
HALO_PRIMS = ("all_to_all", "ppermute")
# the pallas_p2p lowering's collective is a pallas_call whose kernel
# issues remote DMAs: dma_start eqns carrying a LOGICAL device id
# (ops.pallas_p2p). Plain in-kernel copies are dma_start without one.
REMOTE_DMA_PRIM = "dma_start"
CALLBACK_PRIMS = (
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call", "python_callback",
)


def walk_eqns(jaxpr, visit) -> None:
    """Call ``visit(eqn)`` on every eqn, recursing into sub-jaxprs
    (pjit/shard_map/custom_vjp/custom_jvp/scan/remat bodies). The ONE
    canonical traversal — the dtype-discipline tests and every collector
    below share it, so descent logic cannot drift between checks."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for p in eqn.params.values():
            for item in p if isinstance(p, (list, tuple)) else [p]:
                inner = getattr(item, "jaxpr", None)
                if inner is not None:
                    walk_eqns(getattr(inner, "jaxpr", inner), visit)
                elif hasattr(item, "eqns"):
                    walk_eqns(item, visit)


def aval_bytes(aval) -> int:
    from dgraph_tpu.plan import dtype_nbytes

    shape = tuple(getattr(aval, "shape", ()) or ())
    return int(math.prod(shape)) * dtype_nbytes(aval.dtype)


def _remote_put_count(call_jaxpr) -> int:
    """Remote-DMA puts (dma_start with a LOGICAL device id) inside one
    pallas_call's kernel jaxpr."""
    count = 0

    def visit(eqn):
        nonlocal count
        if eqn.primitive.name == REMOTE_DMA_PRIM:
            did = eqn.params.get("device_id_type")
            if did is not None and "logical" in str(did).lower():
                count += 1

    walk_eqns(call_jaxpr, visit)
    return count


def collect_collectives(jaxpr) -> dict:
    """One pass over a (closed) jaxpr: every halo collective / psum /
    host-callback eqn with operand shapes, dtypes, and bytes.

    ``pallas_p2p`` entries are pallas_calls whose kernel issues remote
    puts; the recorded operand is the ``[n_deltas, S, F]`` send-tile
    stack (the unique float rank-3 operand of the transport kernel) and
    ``puts`` the number of remote DMAs inside — the auditable analogue
    of one collective eqn's operand + round count."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out = {
        "all_to_all": [], "ppermute": [], "pallas_p2p": [], "psum": [],
        "callbacks": [],
    }

    def visit(eqn):
        name = eqn.primitive.name
        if name == "pallas_call":
            inner = eqn.params.get("jaxpr")
            if inner is None:
                return
            puts = _remote_put_count(getattr(inner, "jaxpr", inner))
            if not puts:
                return
            rank3 = [
                v.aval for v in eqn.invars
                if hasattr(getattr(v, "aval", None), "shape")
                and len(v.aval.shape) == 3
            ]
            blocks = [a for a in rank3 if "int" not in str(a.dtype)]
            if not blocks:
                # fp8 wire payloads ride as uint8: the encoded send-tile
                # stack is still the transport's one rank-3 payload (the
                # index operands the float filter exists to skip are i32)
                blocks = [a for a in rank3 if str(a.dtype) == "uint8"]
            for aval in blocks[:1]:
                out["pallas_p2p"].append({
                    "primitive": "pallas_p2p",
                    "shape": tuple(int(s) for s in aval.shape),
                    "dtype": str(aval.dtype),
                    "bytes": aval_bytes(aval),
                    "puts": puts,
                })
            if not blocks:
                out["pallas_p2p"].append({
                    "primitive": "pallas_p2p", "shape": (), "dtype": "?",
                    "bytes": 0, "puts": puts,
                })
            return
        if name in HALO_PRIMS:
            key = name
        elif name in PSUM_PRIMS:
            key = "psum"
        elif name in CALLBACK_PRIMS:
            key = "callbacks"
        else:
            return
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            # scalars have shape () and still count (the loss psum is one);
            # only truly shapeless vars (tokens etc.) are skipped
            if aval is None or not hasattr(aval, "shape"):
                if key == "callbacks":
                    out[key].append({"primitive": name})
                continue
            out[key].append({
                "primitive": name,
                "shape": tuple(int(s) for s in aval.shape),
                "dtype": str(aval.dtype),
                "bytes": aval_bytes(aval),
            })

    walk_eqns(jaxpr, visit)
    return out


# ---------------------------------------------------------------------------
# audit workload: a small sharded GCN train/eval/serve triple
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AuditWorkload:
    """Everything needed to trace the three program kinds abstractly."""

    model: Any
    optimizer: Any
    mesh: Any
    plan: Any          # numpy-leaf EdgePlan (stacked [W] layout)
    plan_np: Any       # same object, kept for footprint accounting
    batch: dict        # numpy leaves, leading [W]
    params: Any        # ShapeDtypeStruct pytree
    opt_state: Any     # ShapeDtypeStruct pytree
    world_size: int
    feat_dim: int
    num_nodes: int
    serve_bucket: int = 8


def workload_from_plan(
    plan,
    *,
    feat_dim: int = 8,
    hidden: int = 16,
    num_classes: int = 4,
    num_layers: int = 2,
    seed: int = 0,
    compute_dtype: Optional[str] = "bfloat16",
    devices=None,
    batch: Optional[dict] = None,
    num_nodes: Optional[int] = None,
) -> AuditWorkload:
    """Scaffold the audit workload around an EXISTING ``[W]``-stacked
    plan: mesh, communicator, bf16-compute GCN, batch (zeros unless
    given — operand values never reach a lowered artifact), and abstract
    ``eval_shape`` params/opt_state.  The ONE builder
    :func:`build_audit_workload` and the cross-rank spmd tier's per-rank
    builds (:func:`dgraph_tpu.analysis.spmd.build_rank_workload`) both
    go through, so the tiers can never audit different workload shapes.
    Nothing here compiles and nothing touches a device buffer."""
    import numpy as np
    import jax
    import optax

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.comm.mesh import (
        GRAPH_AXIS, make_graph_mesh, plan_in_specs, squeeze_plan,
    )
    from dgraph_tpu.models import GCN
    from jax.sharding import PartitionSpec as P

    world_size = int(plan.world_size)
    if devices is None:
        devices = jax.devices()
    if len(devices) < world_size:
        raise ValueError(
            f"audit for world_size={world_size} needs that many "
            f"devices; have {len(devices)} (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=8 before jax's first "
            f"backend touch)"
        )
    mesh = make_graph_mesh(
        ranks_per_graph=world_size, devices=devices[:world_size]
    )
    comm = Communicator.init_process_group("tpu", world_size=world_size)
    dt = None
    if compute_dtype and compute_dtype not in ("float32", "f32"):
        import jax.numpy as jnp

        dt = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
              "float16": jnp.float16}[compute_dtype]
    model = GCN(
        hidden_features=hidden, out_features=num_classes, comm=comm,
        num_layers=num_layers, dtype=dt,
    )
    n_pad = int(plan.n_src_pad)
    if batch is None:
        batch = {
            "x": np.zeros((world_size, n_pad, feat_dim), np.float32),
            "y": np.zeros((world_size, n_pad), np.int32),
            "mask": np.ones((world_size, n_pad), np.float32),
        }

    def init_body(b, p):
        ps = squeeze_plan(p)
        bb = jax.tree.map(lambda leaf: leaf[0], b)
        return model.init(jax.random.key(seed), bb["x"], ps)

    from dgraph_tpu.comm.collectives import shard_map_checks

    bspecs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
    init_fn = jax.shard_map(
        init_body, mesh=mesh, in_specs=(bspecs, plan_in_specs(plan)),
        out_specs=P(),
        **shard_map_checks(relax="init outputs replicated by construction"),
    )
    params = jax.eval_shape(init_fn, batch, plan)
    optimizer = optax.adam(1e-2)
    opt_state = jax.eval_shape(optimizer.init, params)
    return AuditWorkload(
        model=model, optimizer=optimizer, mesh=mesh, plan=plan, plan_np=plan,
        batch=batch, params=params, opt_state=opt_state,
        world_size=world_size, feat_dim=feat_dim,
        num_nodes=num_nodes if num_nodes is not None
        else world_size * n_pad,
    )


def build_audit_workload(
    world_size: int = 2,
    *,
    num_nodes: int = 48,
    num_edges: int = 300,
    feat_dim: int = 8,
    hidden: int = 16,
    num_classes: int = 4,
    num_layers: int = 2,
    seed: int = 0,
    compute_dtype: Optional[str] = "bfloat16",
    devices=None,
) -> AuditWorkload:
    """Host-build the canonical audit workload: a ``world_size``-shard
    random graph (with the interior/boundary split, so all three lowerings
    are legal) and a bf16-compute GCN — bf16 makes the fp32-accumulation
    check bite.  No device arrays: params/opt_state are
    ``ShapeDtypeStruct`` trees from ``eval_shape`` and the batch is plain
    numpy, so tracing compiles nothing."""
    import numpy as np

    from dgraph_tpu import plan as pl

    rng = np.random.default_rng(seed)
    part = np.sort(rng.integers(0, world_size, num_nodes)).astype(np.int32)
    edges = np.stack([
        rng.integers(0, num_nodes, num_edges),
        rng.integers(0, num_nodes, num_edges),
    ])
    plan, layout = pl.build_edge_plan(
        edges, part, world_size=world_size, overlap=True
    )
    x = pl.shard_vertex_data(
        rng.normal(size=(num_nodes, feat_dim)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    )
    batch = {
        "x": x,
        "y": np.zeros((world_size, plan.n_src_pad), np.int32),
        "mask": np.ones((world_size, plan.n_src_pad), np.float32),
    }
    return workload_from_plan(
        plan, feat_dim=feat_dim, hidden=hidden, num_classes=num_classes,
        num_layers=num_layers, seed=seed, compute_dtype=compute_dtype,
        devices=devices, batch=batch, num_nodes=num_nodes,
    )


# ---------------------------------------------------------------------------
# program builders (fresh per lowering: jit's trace cache would otherwise
# replay the first lowering it saw — exactly the class of staleness the
# auditor exists to expose)
# ---------------------------------------------------------------------------


def _train_program(w: AuditWorkload):
    from dgraph_tpu.train.loop import make_train_step

    step = make_train_step(w.model, w.optimizer, w.mesh, w.plan)
    return step, (w.params, w.opt_state, w.batch, w.plan)


def _eval_program(w: AuditWorkload):
    from dgraph_tpu.train.loop import make_eval_step

    step = make_eval_step(w.model, w.mesh)
    return step, (w.params, w.batch, w.plan)


def _serve_program(w: AuditWorkload):
    """The engine's per-bucket forward, built by the REAL
    :class:`~dgraph_tpu.serve.engine.ServeEngine` construction path (so
    serve semantics cannot drift from what is audited), traced with
    abstract operands."""
    import numpy as np
    import jax

    from dgraph_tpu.serve.bucketing import BucketLadder
    from dgraph_tpu.serve.engine import ServeEngine

    params_zero = jax.tree.map(
        lambda s: np.zeros(s.shape, s.dtype), w.params
    )
    engine = ServeEngine(
        w.model, w.mesh, w.plan, params_zero,
        {"x": w.batch["x"]},
        id_rank=np.zeros(w.num_nodes, np.int32),
        id_slot=np.zeros(w.num_nodes, np.int32),
        ladder=BucketLadder((w.serve_bucket,)),
    )
    fwd = engine._forwards[w.serve_bucket]
    idx = jax.ShapeDtypeStruct((w.serve_bucket,), np.int32)
    return fwd, (w.params, {"x": w.batch["x"]}, w.plan, idx, idx)


PROGRAMS = {
    "train_step": _train_program,
    "eval_step": _eval_program,
    "serve_forward": _serve_program,
}


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------


def _expected_bytes(plan, dtype: str, feat_dim: int) -> dict:
    """What obs.footprint prices for ONE exchange at this width/dtype:
    the padded all_to_all operand and the per-round ppermute block. Pulled
    from :func:`plan_footprint` itself (not re-derived) so the audit pins
    the exact numbers the tuner ranks on.

    ``dtype``/``feat_dim`` come from the TRACED collective operand. Under
    a non-identity wire format that operand is already encoded — bf16
    casts price themselves (footprint resolves the same format at the
    traced itemsize), but the fp8 operand is uint8 with the 4 scale lanes
    concatenated into its last axis, so the activation width is recovered
    before pricing (wire_row_bytes then reproduces the traced last-dim
    exactly)."""
    from dgraph_tpu.obs.footprint import plan_footprint
    from dgraph_tpu.wire.spec import FP8_SCALE_BYTES, resolve_wire_format

    wf, _src = resolve_wire_format(
        plan.world_size, tuple(plan.halo_deltas),
        plan_format=getattr(plan, "wire_format", "fp32"),
    )
    if wf == "fp8" and dtype == "uint8":
        feat_dim = feat_dim - FP8_SCALE_BYTES
    fp = plan_footprint(plan, dtype, feat_dim=feat_dim)
    ex = fp["collectives"]["halo_exchange"]
    n_deltas = fp["num_halo_deltas"]
    per_round = (
        fp["halo"]["wire_bytes_per_shard"]["ppermute"] // n_deltas
        if n_deltas else 0
    )
    sched_fp = ex.get("sched") or {}
    return {
        "a2a_operand_bytes": ex["a2a_operand_bytes_per_shard"],
        "ppermute_round_bytes": per_round,
        # the p2p transport's one [n_deltas, S, F] send-tile stack — the
        # same boundary-only bytes the ppermute rounds move in total
        "p2p_operand_bytes": fp["halo"]["wire_bytes_per_shard"]["pallas_p2p"],
        # the compiled schedule's per-round operand bytes (rounds differ
        # in height, so this is a LIST — the audit compares multisets)
        "sched_round_bytes": list(sched_fp.get("round_bytes_per_shard", [])),
        "num_halo_deltas": n_deltas,
    }


def _audit_one_program(
    label: str, impl: str, fn: Callable, args: tuple, plan, failures: list,
) -> dict:
    """Trace one program under one pinned lowering and run the per-program
    checks; returns the program record (and appends to ``failures``)."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    coll = collect_collectives(jaxpr)
    n_a2a, n_pp = len(coll["all_to_all"]), len(coll["ppermute"])
    n_p2p = len(coll["pallas_p2p"])

    def fail(msg):
        failures.append(f"[{label}/{impl}] {msg}")

    # exactly one halo-lowering family per traced program (PR 4 hazard) —
    # the pallas_p2p puts are a third family the same rule covers
    families_present = [
        name for name, count in (
            ("all_to_all", n_a2a), ("ppermute", n_pp), ("pallas_p2p", n_p2p),
        ) if count
    ]
    if len(families_present) > 1:
        fail(
            f"mixed halo lowerings in ONE program: "
            + " + ".join(
                f"{len(coll[f])} {f}" for f in families_present
            )
            + " eqns (two legs of one op resolved differently)"
        )
    want_family = impl if impl in ("all_to_all", "pallas_p2p") else "ppermute"
    for other in ("all_to_all", "ppermute", "pallas_p2p"):
        if other != want_family and coll[other]:
            fail(
                f"pinned lowering {impl!r} but the trace contains "
                f"{len(coll[other])} {other} eqn(s)"
            )
    if not coll[want_family]:
        fail(f"pinned lowering {impl!r} traced no {want_family} eqns at all")

    # operand bytes: every collective operand must be EXACTLY the block
    # obs.footprint prices at that operand's width/dtype
    byte_rows = []
    for rec in coll[want_family]:
        feat = rec["shape"][-1] if rec["shape"] else 0
        exp = _expected_bytes(plan, rec["dtype"], feat)
        if impl == "sched":
            # compiled-schedule rounds differ in height, so each traced
            # operand must be SOME priced round (membership here); the
            # full multiset equality — every round present exactly legs
            # times — is pinned cross-program in audit_workload
            allowed = set(exp["sched_round_bytes"])
            member = rec["bytes"] in allowed
            byte_rows.append({
                "primitive": rec["primitive"], "shape": rec["shape"],
                "dtype": rec["dtype"], "traced_bytes": rec["bytes"],
                "footprint_bytes": rec["bytes"] if member else 0,
            })
            if not member:
                fail(
                    f"{rec['primitive']} operand {rec['shape']} "
                    f"({rec['dtype']}) carries {rec['bytes']} B; footprint "
                    f"prices rounds of {sorted(allowed)} B — the traced "
                    f"round is not one the compiled schedule contains"
                )
            continue
        want = {
            "all_to_all": exp["a2a_operand_bytes"],
            "ppermute": exp["ppermute_round_bytes"],
            "pallas_p2p": exp["p2p_operand_bytes"],
        }[want_family]
        byte_rows.append({
            "primitive": rec["primitive"], "shape": rec["shape"],
            "dtype": rec["dtype"], "traced_bytes": rec["bytes"],
            "footprint_bytes": want,
        })
        if rec["bytes"] != want:
            fail(
                f"{rec['primitive']} operand {rec['shape']} ({rec['dtype']})"
                f" carries {rec['bytes']} B; footprint prices {want} B — "
                f"the tuner is ranking a schedule the program does not emit"
            )
        if want_family == "pallas_p2p" and rec.get("puts") != exp[
            "num_halo_deltas"
        ]:
            fail(
                f"pallas_p2p transport issues {rec.get('puts')} remote "
                f"put(s); the plan has {exp['num_halo_deltas']} live "
                f"delta(s) — one put per live delta per leg"
            )

    # no host callbacks inside traced code
    if coll["callbacks"]:
        fail(
            f"host callback(s) inside the traced program: "
            f"{sorted({c['primitive'] for c in coll['callbacks']})}"
        )

    # fp32 accumulation: psum-family reductions must not run sub-32-bit
    narrow = [
        r for r in coll["psum"]
        if r["dtype"] in ("bfloat16", "float16")
    ]
    if narrow:
        fail(
            f"psum on a sub-32-bit dtype: "
            f"{[(r['shape'], r['dtype']) for r in narrow[:4]]} — fp32 "
            f"accumulation discipline broken"
        )

    return {
        "program": label,
        "impl": impl,
        "num_all_to_all": n_a2a,
        "num_ppermute": n_pp,
        "num_pallas_p2p": n_p2p,
        "num_remote_puts": sum(r.get("puts", 0) for r in coll["pallas_p2p"]),
        "num_psum": len(coll["psum"]),
        "collective_operands": byte_rows,
    }


def donation_unmatched(fn, args, donated_tree) -> dict:
    """(shape, dtype) -> count of donated leaves with NO matching output
    leaf in ``jax.eval_shape(fn, *args)`` (abstract — never compiles).
    Empty dict == every donation can be honored."""
    import jax
    from collections import Counter

    out = jax.eval_shape(fn, *args)
    donated = Counter(
        (tuple(l.shape), str(l.dtype)) for l in jax.tree.leaves(donated_tree)
    )
    produced = Counter(
        (tuple(l.shape), str(l.dtype)) for l in jax.tree.leaves(out)
    )
    return {
        k: n - produced.get(k, 0)
        for k, n in donated.items()
        if n > produced.get(k, 0)
    }


def _audit_donation(w: AuditWorkload, failures: list) -> dict:
    """The train step donates (params, opt_state); every donated leaf's
    (shape, dtype) must be matched by an output leaf, or XLA drops the
    donation and peak HBM grows by the donated footprint."""
    import jax

    step, args = _train_program(w)
    unmatched = donation_unmatched(step, args, (w.params, w.opt_state))
    donated_count = len(jax.tree.leaves((w.params, w.opt_state)))
    if unmatched:
        failures.append(
            f"[train_step] donated buffers not consumed by any same-"
            f"shape/dtype output (donation silently dropped): "
            f"{dict(list(unmatched.items())[:4])}"
        )
    return {
        "donated_leaves": donated_count,
        "unmatched": [
            {"shape": list(k[0]), "dtype": k[1], "count": n}
            for k, n in unmatched.items()
        ],
    }


def audit_workload(
    w: AuditWorkload,
    impls=HALO_IMPLS,
    programs=None,
) -> dict:
    """Trace every (program, lowering) pair and verify the full contract.

    Returns an ``AuditReport`` dict (``kind="trace_audit"``); ``ok`` is
    False and ``failures`` names every drift.  The caller decides whether
    to raise (the CLI exits nonzero; bench's fallback just attaches it).
    """
    from dgraph_tpu import config as _cfg

    failures: list = []
    program_records = []
    legs: dict = {}
    saved = (_cfg.halo_impl, _cfg.tuned_halo_impl, _cfg.use_pallas_p2p)
    audited_impls = [
        impl for impl in impls
        if impl != "sched"
        or getattr(w.plan_np, "halo_schedule", None) is not None
    ]
    try:
        for impl in audited_impls:
            _cfg.set_flags(halo_impl=impl, tuned_halo_impl=None)
            # pinning pallas_p2p on a chip-less backend needs the explicit
            # availability opt-in (the kernels trace in interpret mode —
            # still zero compiles under make_jaxpr)
            _cfg.set_flags(
                use_pallas_p2p=True if impl == "pallas_p2p" else saved[2]
            )
            for label, build in (programs or PROGRAMS).items():
                fn, args = build(w)
                rec = _audit_one_program(
                    label, impl, fn, args, w.plan_np, failures
                )
                program_records.append(rec)
                if impl == "all_to_all":
                    legs[label] = rec["num_all_to_all"]
    finally:
        _cfg.set_flags(
            halo_impl=saved[0], tuned_halo_impl=saved[1],
            use_pallas_p2p=saved[2],
        )

    # cross-lowering count pin: the round-based lowerings must run exactly
    # legs * num_halo_deltas rounds (pallas_p2p: legs transports carrying
    # legs * num_halo_deltas remote puts), where legs is measured from
    # the all_to_all-pinned trace of the SAME program (model-agnostic:
    # the exchange-leg count is a property of the program, not the
    # lowering)
    n_deltas = len(w.plan_np.halo_deltas)
    for rec in program_records:
        if rec["impl"] == "all_to_all" or rec["program"] not in legs:
            continue
        if rec["impl"] == "pallas_p2p":
            want_t = legs[rec["program"]]
            want_puts = want_t * n_deltas
            if rec["num_pallas_p2p"] != want_t:
                failures.append(
                    f"[{rec['program']}/{rec['impl']}] "
                    f"{rec['num_pallas_p2p']} p2p transports; expected one "
                    f"per exchange leg = {want_t}"
                )
            if rec["num_remote_puts"] != want_puts:
                failures.append(
                    f"[{rec['program']}/{rec['impl']}] "
                    f"{rec['num_remote_puts']} remote puts; expected "
                    f"legs({want_t}) * num_halo_deltas({n_deltas}) = "
                    f"{want_puts}"
                )
            continue
        if rec["impl"] == "sched":
            # the compiled schedule replays num_rounds ppermutes per
            # exchange leg, and the traced per-(dtype, width) byte
            # multiset must equal the footprint-priced rounds repeated
            # once per leg — byte-exact, order-free
            schedule = w.plan_np.halo_schedule
            n_rounds = schedule.num_rounds
            want = legs[rec["program"]] * n_rounds
            if rec["num_ppermute"] != want:
                failures.append(
                    f"[{rec['program']}/{rec['impl']}] "
                    f"{rec['num_ppermute']} ppermute rounds; expected "
                    f"legs({legs[rec['program']]}) * "
                    f"schedule rounds({n_rounds}) = {want}"
                )
                continue
            groups: dict = {}
            for o in rec["collective_operands"]:
                feat = o["shape"][-1] if o["shape"] else 0
                groups.setdefault((o["dtype"], feat), []).append(
                    o["traced_bytes"]
                )
            for (dt, feat), traced in sorted(groups.items()):
                exp = _expected_bytes(
                    w.plan_np, dt, feat
                )["sched_round_bytes"]
                k, r = divmod(len(traced), max(len(exp), 1))
                if not exp or r or sorted(traced) != sorted(exp * k):
                    failures.append(
                        f"[{rec['program']}/{rec['impl']}] traced round "
                        f"bytes at ({dt}, F={feat}) "
                        f"{sorted(traced)[:8]} != footprint rounds "
                        f"{sorted(exp)[:8]} x {k} leg(s)"
                    )
            continue
        want = legs[rec["program"]] * n_deltas
        if rec["num_ppermute"] != want:
            failures.append(
                f"[{rec['program']}/{rec['impl']}] {rec['num_ppermute']} "
                f"ppermute rounds; expected legs({legs[rec['program']]}) * "
                f"num_halo_deltas({n_deltas}) = {want}"
            )

    donation = _audit_donation(w, failures)
    return {
        "kind": "trace_audit",
        "world_size": w.world_size,
        "num_nodes": w.num_nodes,
        "num_halo_deltas": n_deltas,
        "impls": list(audited_impls),
        "exchange_legs": legs,
        "programs": program_records,
        "donation": donation,
        "failures": failures,
        "ok": not failures,
    }


def schedule_drift_record(
    world_size: int = 8, *, num_nodes: int = 4096, num_edges: int = 16384,
    feat_dim: int = 32, seed: int = 0,
) -> dict:
    """Compact footprint-vs-traced comparison for bench's no-healthy-chip
    fallback tier (ROADMAP item 5): one record per halo lowering with the
    traced and footprint-priced bytes, so a round that never reaches a
    chip still lands a non-null schedule-drift signal."""
    w = build_audit_workload(
        world_size, num_nodes=num_nodes, num_edges=num_edges,
        feat_dim=feat_dim, seed=seed,
    )
    report = audit_workload(w)
    per_impl = {}
    for rec in report["programs"]:
        if rec["program"] != "train_step":
            continue
        ops = rec["collective_operands"]
        per_impl[rec["impl"]] = {
            "collective_count": len(ops),
            "traced_bytes": sum(o["traced_bytes"] for o in ops),
            "footprint_bytes": sum(o["footprint_bytes"] for o in ops),
        }
    return {
        "kind": "schedule_drift",
        "workload": {
            "world_size": world_size, "nodes": num_nodes, "edges": num_edges,
            "feat_dim": feat_dim, "seed": seed,
        },
        "num_halo_deltas": report["num_halo_deltas"],
        "train_step_by_impl": per_impl,
        "failures": report["failures"],
        "drift": not report["ok"],
    }
