"""ctypes loader for the native host toolkit (csrc/dgraph_host.cpp).

Builds the shared library on first use (g++, no pybind11 — SURVEY
environment constraints) and exposes numpy-friendly wrappers. Every caller
keeps a pure-numpy fallback; ``available()`` gates the dispatch — the
reference's CUDA-or-torch dual-implementation pattern
(``RankLocalOps.py:21-31``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_SO = os.path.join(_CSRC, "build", "libdgraph_host.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO):
            try:
                subprocess.run(
                    ["make", "-C", _CSRC],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _build_failed = True
            return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.greedy_bfs_partition.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, i32p,
        ]
        lib.greedy_bfs_partition.restype = None
        lib.multilevel_partition_c.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, i32p,
        ]
        lib.multilevel_partition_c.restype = None
        lib.unique_encoded_pairs.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p,
        ]
        lib.unique_encoded_pairs.restype = ctypes.c_int64
        lib.edge_cut_count.argtypes = [i64p, i64p, ctypes.c_int64, i32p]
        lib.edge_cut_count.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def greedy_bfs_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    out = np.empty(num_nodes, np.int32)
    lib.greedy_bfs_partition(src, dst, len(src), num_nodes, world_size, seed, out)
    return out


def multilevel_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """METIS-shaped multilevel k-way partition (csrc/dgraph_host.cpp):
    heavy-edge-matching coarsening, weighted greedy initial partition,
    boundary (FM-lite) refinement per uncoarsening level."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    out = np.empty(num_nodes, np.int32)
    lib.multilevel_partition_c(src, dst, len(src), num_nodes, world_size, seed, out)
    return out


def unique_encoded_pairs(keys: np.ndarray, vals: np.ndarray, stride: int) -> np.ndarray:
    lib = _load()
    assert lib is not None, "native library unavailable"
    keys = np.ascontiguousarray(keys, np.int64)
    vals = np.ascontiguousarray(vals, np.int64)
    out = np.empty(len(keys), np.int64)
    m = lib.unique_encoded_pairs(keys, vals, len(keys), stride, out)
    return out[:m]


def edge_cut_count(edge_index: np.ndarray, partition: np.ndarray) -> int:
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    part = np.ascontiguousarray(partition, np.int32)
    return int(lib.edge_cut_count(src, dst, len(src), part))
