"""ctypes loader for the native host toolkit (csrc/dgraph_host.cpp).

Builds the shared library on first use (g++, no pybind11 — SURVEY
environment constraints) and exposes numpy-friendly wrappers. Every caller
keeps a pure-numpy fallback; ``available()`` gates the dispatch — the
reference's CUDA-or-torch dual-implementation pattern
(``RankLocalOps.py:21-31``).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc")
_SO = os.path.join(_CSRC, "build", "libdgraph_host.so")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        # Rebuild BEFORE the first dlopen when the .so is missing or older
        # than its source: once a stale library is CDLL'd, re-dlopening the
        # same path returns the already-loaded handle (ctypes never
        # dlcloses), so probe-then-rebuild cannot recover in-process.
        src = os.path.join(_CSRC, "dgraph_host.cpp")
        stale = not os.path.exists(_SO) or (
            os.path.exists(src) and os.path.getmtime(_SO) < os.path.getmtime(src)
        )
        if stale:
            try:
                subprocess.run(
                    ["make", "-B", "-C", _CSRC],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            except Exception:
                _build_failed = True
                return None
        lib = None
        try:
            lib = ctypes.CDLL(_SO)
            lib.refine_weighted_csr_c  # newest entry point; missing = stale build
        except OSError:
            # a corrupt/truncated .so (interrupted link) fails CDLL outright
            # — no handle was cached, so ONE rebuild-and-retry is safe
            # (unlike the symbol-missing case, where the stale handle would
            # be returned by any further dlopen of the same path)
            try:
                subprocess.run(
                    ["make", "-B", "-C", _CSRC],
                    check=True, capture_output=True, timeout=120,
                )
                lib = ctypes.CDLL(_SO)
                lib.refine_weighted_csr_c
            except Exception:
                lib = None
        except AttributeError:
            lib = None
        if lib is None:
            _build_failed = True
            return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.greedy_bfs_partition.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, i32p,
        ]
        lib.greedy_bfs_partition.restype = None
        lib.multilevel_partition_c.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_uint64, i32p,
        ]
        lib.multilevel_partition_c.restype = None
        lib.unique_encoded_pairs.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p,
        ]
        lib.unique_encoded_pairs.restype = ctypes.c_int64
        lib.multilevel_partition_w_c.argtypes = [
            i64p, i64p, i64p, ctypes.c_int64, i64p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_uint64, i32p,
        ]
        lib.multilevel_partition_w_c.restype = None
        lib.multilevel_partition_vw_c.argtypes = [
            i64p, i64p, ctypes.c_int64, i64p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_uint64, i32p,
        ]
        lib.multilevel_partition_vw_c.restype = None
        lib.cluster_coarsen_c.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, i64p,
        ]
        lib.cluster_coarsen_c.restype = ctypes.c_int64
        lib.refine_unweighted_csr_c.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_double, i32p,
        ]
        # int status (0 ok, -1 = int32 CSR id bound refused), mirroring
        # cluster_coarsen_c: a no-op refine must be detectable by any
        # caller, not just the Python wrappers' pre-check (ADVICE r5)
        lib.refine_unweighted_csr_c.restype = ctypes.c_int32
        lib.refine_weighted_csr_c.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_double, i64p, i32p,
        ]
        lib.refine_weighted_csr_c.restype = ctypes.c_int32
        lib.edge_cut_count.argtypes = [i64p, i64p, ctypes.c_int64, i32p]
        lib.edge_cut_count.restype = ctypes.c_int64
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.plan_core_begin.argtypes = [
            i64p, i64p, ctypes.c_int64,          # src, dst, E
            i32p, i32p,                          # src_part, dst_part
            i64p, i64p,                          # src_offsets, dst_offsets
            ctypes.c_int64, ctypes.c_int64,      # v_src, v_dst
            ctypes.c_int32, ctypes.c_int32,      # W, edge_owner_dst
            i64p,                                # out_sizes[4]
        ]
        lib.plan_core_begin.restype = ctypes.c_void_p
        lib.plan_core_fill.argtypes = [
            ctypes.c_void_p, i64p, i64p, i64p, i64p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i32p, i32p, f32p,                    # src_index, dst_index, edge_mask
            i32p, f32p,                          # send_idx, send_mask
            i64p, i32p, i64p,                    # halo_counts, edge_rank, edge_slot
        ]
        lib.plan_core_fill.restype = None
        lib.plan_core_free.argtypes = [ctypes.c_void_p]
        lib.plan_core_free.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def greedy_bfs_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    out = np.empty(num_nodes, np.int32)
    lib.greedy_bfs_partition(src, dst, len(src), num_nodes, world_size, seed, out)
    return out


def multilevel_partition(
    edge_index: np.ndarray, num_nodes: int, world_size: int, seed: int = 0
) -> np.ndarray:
    """METIS-shaped multilevel k-way partition (csrc/dgraph_host.cpp):
    heavy-edge-matching coarsening, weighted greedy initial partition,
    boundary (FM-lite) refinement per uncoarsening level."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    out = np.empty(num_nodes, np.int32)
    lib.multilevel_partition_c(src, dst, len(src), num_nodes, world_size, seed, out)
    return out


def cluster_coarsen(
    edge_index: np.ndarray, num_nodes: int, max_cluster_weight: int,
    seed: int = 0,
) -> tuple[np.ndarray, int]:
    """Capped greedy cluster coarsening (csrc ``cluster_coarsen_c``):
    one int32 CSR + O(V) state instead of the WGraph stack. Returns
    (cmap[V] int64 cluster ids, num_clusters)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    cmap = np.empty(num_nodes, np.int64)
    nc = lib.cluster_coarsen_c(
        src, dst, len(src), num_nodes, max_cluster_weight, seed, cmap
    )
    if nc < 0:
        raise ValueError(
            f"cluster_coarsen: {num_nodes} vertices exceed the int32 CSR "
            "id bound (2^31-1)"
        )
    return cmap, int(nc)


def multilevel_partition_weighted(
    pair_src: np.ndarray, pair_dst: np.ndarray, pair_w: np.ndarray,
    vertex_w: np.ndarray, num_vertices: int, world_size: int, seed: int = 0,
) -> np.ndarray:
    """Multilevel k-way partition of a weighted graph given as unique
    undirected pairs (u < v) + weights; balance objective is summed vertex
    weight (so cluster-coarsened supernodes stay fine-balanced)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    out = np.empty(num_vertices, np.int32)
    lib.multilevel_partition_w_c(
        np.ascontiguousarray(pair_src, np.int64),
        np.ascontiguousarray(pair_dst, np.int64),
        np.ascontiguousarray(pair_w, np.int64),
        len(pair_src),
        np.ascontiguousarray(vertex_w, np.int64),
        num_vertices, world_size, seed, out,
    )
    return out


def multilevel_partition_vertex_weighted(
    edge_index: np.ndarray, vertex_w: np.ndarray, num_nodes: int,
    world_size: int, seed: int = 0,
) -> np.ndarray:
    """Multilevel k-way partition of a raw edge list balancing summed
    CALLER vertex weights (e.g. 1 + alpha*degree to co-balance edges —
    see multilevel_partition_vw_c)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    out = np.empty(num_nodes, np.int32)
    lib.multilevel_partition_vw_c(
        np.ascontiguousarray(edge_index[0], np.int64),
        np.ascontiguousarray(edge_index[1], np.int64),
        edge_index.shape[1],
        np.ascontiguousarray(vertex_w, np.int64),
        num_nodes, world_size, seed, out,
    )
    return out


def refine_unweighted_csr(
    edge_index: np.ndarray, num_nodes: int, world_size: int,
    part: np.ndarray, passes: int = 3, imbalance: float = 1.03,
) -> np.ndarray:
    """In-place greedy boundary refinement on the fine graph (unit
    weights, one int32 CSR). Returns ``part`` (modified in place when it
    was already a contiguous int32 array)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    if num_nodes >= 2**31 - 1:
        # the C side would silently no-op (build_csr32 refuses); the
        # refine stage is load-bearing for multilevel_sampled, so fail
        # loudly like cluster_coarsen does
        raise ValueError(
            f"refine_unweighted_csr: {num_nodes} vertices exceed the "
            "int32 CSR id bound (2^31-1)"
        )
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    part = np.ascontiguousarray(part, np.int32)
    status = lib.refine_unweighted_csr_c(
        src, dst, len(src), num_nodes, world_size, passes, imbalance, part
    )
    # belt-and-braces behind the pre-check above: the C side now reports
    # its refusal instead of silently returning the input unrefined
    if status != 0:
        raise RuntimeError(
            f"refine_unweighted_csr_c returned status {status} (int32 "
            "CSR id bound refused); partition left unrefined"
        )
    return part


def refine_weighted_csr(
    edge_index: np.ndarray, vertex_w: np.ndarray, num_nodes: int,
    world_size: int, part: np.ndarray, passes: int = 3,
    imbalance: float = 1.03,
) -> np.ndarray:
    """Greedy boundary refinement with a Σ vertex-weight balance cap (cut
    gain stays unit edge counts). The edge-balance blend must refine
    under the SAME weights it partitioned with — a unit-count refine
    undoes the blend (see refine_weighted_csr_c)."""
    lib = _load()
    assert lib is not None, "native library unavailable"
    if num_nodes >= 2**31 - 1:
        raise ValueError(
            f"refine_weighted_csr: {num_nodes} vertices exceed the "
            "int32 CSR id bound (2^31-1)"
        )
    part = np.ascontiguousarray(part, np.int32)
    status = lib.refine_weighted_csr_c(
        np.ascontiguousarray(edge_index[0], np.int64),
        np.ascontiguousarray(edge_index[1], np.int64),
        edge_index.shape[1], num_nodes, world_size, passes, imbalance,
        np.ascontiguousarray(vertex_w, np.int64), part,
    )
    if status != 0:
        raise RuntimeError(
            f"refine_weighted_csr_c returned status {status} (int32 "
            "CSR id bound refused); partition left unrefined"
        )
    return part


def unique_encoded_pairs(keys: np.ndarray, vals: np.ndarray, stride: int) -> np.ndarray:
    lib = _load()
    assert lib is not None, "native library unavailable"
    keys = np.ascontiguousarray(keys, np.int64)
    vals = np.ascontiguousarray(vals, np.int64)
    out = np.empty(len(keys), np.int64)
    m = lib.unique_encoded_pairs(keys, vals, len(keys), stride, out)
    return out[:m]


def edge_cut_count(edge_index: np.ndarray, partition: np.ndarray) -> int:
    lib = _load()
    assert lib is not None, "native library unavailable"
    src = np.ascontiguousarray(edge_index[0], np.int64)
    dst = np.ascontiguousarray(edge_index[1], np.int64)
    part = np.ascontiguousarray(partition, np.int32)
    return int(lib.edge_cut_count(src, dst, len(src), part))


class PlanCore:
    """Streaming native plan-build core (csrc/dgraph_host.cpp
    ``plan_core_*``): counting/radix-sort edge ordering + halo-pair dedup
    with bounded memory, for billion-edge plan builds the numpy path's
    lexsort/unique temporaries cannot handle (SURVEY §7; the reference's
    offline per-rank plan precompute, ``MAG240M_dataset.py:237-260``).

    Usage: construct (phase 1: sizes), read ``e_max/s_max/num_pairs``,
    then ``fill(...)`` into preallocated padded arrays; the context frees
    on ``close()`` or GC.
    """

    def __init__(self, src, dst, src_part, dst_part, src_offsets, dst_offsets,
                 world_size: int, edge_owner: str):
        lib = _load()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self._src = np.ascontiguousarray(src, np.int64)
        self._dst = np.ascontiguousarray(dst, np.int64)
        self._soff = np.ascontiguousarray(src_offsets, np.int64)
        self._doff = np.ascontiguousarray(dst_offsets, np.int64)
        sizes = np.zeros(4, np.int64)
        self._ctx = lib.plan_core_begin(
            self._src, self._dst, len(self._src),
            np.ascontiguousarray(src_part, np.int32),
            np.ascontiguousarray(dst_part, np.int32),
            self._soff, self._doff,
            len(src_part), len(dst_part),
            world_size, 1 if edge_owner == "dst" else 0, sizes,
        )
        if not self._ctx:  # not an assert: must survive python -O
            raise ValueError(
                f"plan_core_begin refused E={len(self._src)} (int32 edge/pair "
                "ids bound the native core at 2^31 edges)"
            )
        self.e_max, self.s_max, self.num_pairs, self.num_cross = (
            int(sizes[0]), int(sizes[1]), int(sizes[2]), int(sizes[3]))

    def fill(self, e_pad: int, s_pad: int, n_owner_pad: int, n_halo_pad: int,
             src_index, dst_index, edge_mask, send_idx, send_mask,
             halo_counts, edge_rank, edge_slot) -> None:
        self._lib.plan_core_fill(
            self._ctx, self._src, self._dst, self._soff, self._doff,
            e_pad, s_pad, n_owner_pad, n_halo_pad,
            src_index, dst_index, edge_mask, send_idx, send_mask,
            halo_counts, edge_rank, edge_slot,
        )

    def close(self) -> None:
        if self._ctx:
            self._lib.plan_core_free(self._ctx)
            self._ctx = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
