"""Experiment logging.

Reference parity: ``experiments/OGB/utils.py:12-49`` (rank-0-only
append-to-file experiment logs, ephemeral progress printing, trajectory
plots). On TPU a single controller process drives all devices, so "rank 0
only" is the default reality; the multi-controller case
(``jax.process_index() == 0``) is still honored.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax


def is_lead_process() -> bool:
    return jax.process_index() == 0


def _json_default(o):
    """Coerce the scalar types experiment records actually contain (numpy
    and jax device scalars/arrays) so one un-floated metric doesn't throw
    away a whole record mid-run."""
    if hasattr(o, "item") and getattr(o, "ndim", None) == 0:
        return o.item()
    if hasattr(o, "tolist"):
        return o.tolist()
    return str(o)


class ExperimentLog:
    def __init__(self, path: str, echo: bool = True):
        self.path = path
        self.echo = echo
        if is_lead_process():
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(self.path, "a") as f:
                f.write(f"# log opened {time.strftime('%Y-%m-%d %H:%M:%S')}\n")

    def write(self, record: dict) -> None:
        if not is_lead_process():
            return
        line = json.dumps(record, default=_json_default)
        with open(self.path, "a") as f:
            f.write(line + "\n")
        if self.echo:
            print(line, flush=True)

    def progress(self, msg: str) -> None:
        if is_lead_process():
            print(msg, end="\r", flush=True)
