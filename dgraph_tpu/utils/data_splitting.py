"""Uneven split helpers — parity with ``DGraph/utils.py:17-26``
(largest_split / split_per_rank)."""

from __future__ import annotations


def largest_split(total: int, world_size: int) -> int:
    """ceil(total / world_size): the padded per-rank size."""
    return -(-total // world_size)


def split_per_rank(total: int, rank: int, world_size: int) -> int:
    """Size of rank's slice under ceil-split (last rank may be short)."""
    per = largest_split(total, world_size)
    return max(0, min(per, total - rank * per))
