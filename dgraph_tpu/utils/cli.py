"""Tiny dataclass-CLI bridge: one config tree + ``--key value`` overrides.

Replaces the reference's three config mechanisms (fire.Fire CLIs, dataclass
trees, scattered env flags — SURVEY.md §5 config) with one: a dataclass is
the schema, the CLI overrides fields by name (dotted for nesting).
"""

from __future__ import annotations

import argparse
import dataclasses
import typing


def _sync_platform_from_env() -> None:
    """Make ``JAX_PLATFORMS`` in the environment authoritative for CLI runs.

    Ambient sitecustomize hooks (e.g. the axon TPU tunnel's) may pin
    ``jax_platforms`` via ``jax.config`` at interpreter startup, which
    silently overrides the user's ``JAX_PLATFORMS=cpu`` — so
    ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
    python experiments/...`` would land on 1 real chip instead of the 8
    virtual devices asked for. Re-assert the env var before any backend
    initialization (no-op when they already agree or jax is absent)."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax

        if jax.config.jax_platforms != want:
            jax.config.update("jax_platforms", want)
    except Exception:
        pass


def parse_config(config_cls, argv=None):
    """Build ``config_cls()`` then apply ``--field value`` / ``--a.b value``
    overrides, coercing to the annotated field type."""
    import sys

    _sync_platform_from_env()
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--help" in argv or "-h" in argv:
        print(config_cls.__doc__ or config_cls.__name__)
        for f in dataclasses.fields(config_cls):
            print(f"  --{f.name} (default {f.default!r})")
        raise SystemExit(0)
    cfg = config_cls()

    pairs = []
    it = iter(argv)
    for tok in it:
        if tok.startswith("--"):
            key = tok[2:]
            if "=" in key:
                pairs.append(key.split("=", 1))
            else:
                pairs.append((key, next(it, "true")))
        elif "=" in tok:
            pairs.append(tok.split("=", 1))
        else:
            raise SystemExit(f"override must be key=value or --key value, got {tok!r}")

    for key, raw in pairs:
        obj, parts = cfg, key.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        leaf = parts[-1]
        # get_type_hints resolves STRING annotations (`from __future__
        # import annotations` stringifies every ann — 'Optional[int]',
        # 'int | None', ... would all coerce to str via f.type); it is also
        # the membership check (hasattr would admit properties/methods and
        # then KeyError below)
        hints = typing.get_type_hints(type(obj))
        if leaf not in hints:
            raise SystemExit(f"unknown config field: {key}")
        setattr(obj, leaf, _coerce(raw, hints[leaf]))
    return cfg


def _coerce(raw: str, ann):
    import types

    origin = typing.get_origin(ann)
    if origin in (typing.Union, types.UnionType):  # Optional[X] / X | None
        args = [a for a in typing.get_args(ann) if a is not type(None)]
        if raw.lower() in ("none", "null"):
            return None
        ann = args[0]
    if ann is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if ann in (int, float, str):
        return ann(raw)
    return raw
