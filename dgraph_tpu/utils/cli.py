"""Tiny dataclass-CLI bridge: one config tree + ``--key value`` overrides.

Replaces the reference's three config mechanisms (fire.Fire CLIs, dataclass
trees, scattered env flags — SURVEY.md §5 config) with one: a dataclass is
the schema, the CLI overrides fields by name (dotted for nesting).
"""

from __future__ import annotations

import argparse
import dataclasses
import typing


def parse_config(config_cls, argv=None):
    """Build ``config_cls()`` then apply ``--field value`` / ``--a.b value``
    overrides, coercing to the annotated field type."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--help" in argv or "-h" in argv:
        print(config_cls.__doc__ or config_cls.__name__)
        for f in dataclasses.fields(config_cls):
            print(f"  --{f.name} (default {f.default!r})")
        raise SystemExit(0)
    cfg = config_cls()

    pairs = []
    it = iter(argv)
    for tok in it:
        if tok.startswith("--"):
            key = tok[2:]
            if "=" in key:
                pairs.append(key.split("=", 1))
            else:
                pairs.append((key, next(it, "true")))
        elif "=" in tok:
            pairs.append(tok.split("=", 1))
        else:
            raise SystemExit(f"override must be key=value or --key value, got {tok!r}")

    for key, raw in pairs:
        obj, parts = cfg, key.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        leaf = parts[-1]
        if not hasattr(obj, leaf):
            raise SystemExit(f"unknown config field: {key}")
        ann = {f.name: f.type for f in dataclasses.fields(obj)}[leaf]
        setattr(obj, leaf, _coerce(raw, ann))
    return cfg


def _coerce(raw: str, ann):
    origin = typing.get_origin(ann)
    if origin is typing.Union:  # Optional[...]
        args = [a for a in typing.get_args(ann) if a is not type(None)]
        if raw.lower() in ("none", "null"):
            return None
        ann = args[0]
    if isinstance(ann, str):
        ann = {"int": int, "float": float, "str": str, "bool": bool}.get(ann, str)
    if ann is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if ann in (int, float, str):
        return ann(raw)
    return raw
