from dgraph_tpu.utils.timing import TimingReport
from dgraph_tpu.utils.logging import ExperimentLog
from dgraph_tpu.utils.data_splitting import largest_split, split_per_rank

__all__ = ["TimingReport", "ExperimentLog", "largest_split", "split_per_rank"]
