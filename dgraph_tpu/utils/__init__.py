"""Shared utilities.

Module-level imports here are LAZY (PEP 562 ``__getattr__``) on purpose:
``dgraph_tpu.utils.env`` is the jax-free home of the cross-boundary
env-var constants, imported by modules under the ``jax-free-module``
contract (``chaos``, ``train/supervise.py``, ``comm/membership.py``) —
an eager ``from dgraph_tpu.utils.timing import TimingReport`` here would
drag jax into this package's import and break that contract for every
submodule.  ``from dgraph_tpu.utils import ExperimentLog`` call sites
keep working unchanged through the lazy hook.
"""

from __future__ import annotations

from dgraph_tpu.utils.env import RANK_ENV_VAR

__all__ = [
    "TimingReport", "ExperimentLog", "largest_split", "split_per_rank",
    "RANK_ENV_VAR",
]

_LAZY = {
    "TimingReport": ("dgraph_tpu.utils.timing", "TimingReport"),
    "ExperimentLog": ("dgraph_tpu.utils.logging", "ExperimentLog"),
    "largest_split": ("dgraph_tpu.utils.data_splitting", "largest_split"),
    "split_per_rank": ("dgraph_tpu.utils.data_splitting", "split_per_rank"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: pay the import once
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
