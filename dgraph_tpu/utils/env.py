"""Shared process-identity environment-variable names.

**jax-free by contract** (``analysis.lint``'s ``jax-free-module`` rule):
this module is the one home of the env-var names that cross the
jax-free / jax-using boundary.  ``train/supervise.py`` exports
``DGRAPH_RANK`` to each child of a multi-rank group; ``chaos`` matches a
clause's ``rank=K`` against it; ``comm.membership`` workers read their
member ordinal from it; and ``analysis.lint``'s
``no-rank-branch-in-trace`` rule greps for it inside traced code.  Before
this module, ``train/supervise.py`` hand-copied the literal (it must stay
importable standalone — see its header) — the copies are pinned equal in
``tests/test_plan_shards.py`` so the strings can never drift.

Stdlib-free on purpose: importing this file can never pull in a backend,
a third-party package, or anything a wedged lease could hang.
"""

from __future__ import annotations

# The group supervisor's member ordinal (``supervise_group`` exports it to
# each rank child). Shared group identity: workers read it to know which
# plan shard / checkpoint block is theirs; a chaos clause's ``rank=K``
# matches against it. NEVER read it inside a traced function — that is
# trace-time SPMD divergence, the class ``analysis.spmd`` exists to catch
# (``analysis.lint``'s ``no-rank-branch-in-trace`` flags it at the
# source).
RANK_ENV_VAR = "DGRAPH_RANK"

__all__ = ["RANK_ENV_VAR"]
