"""Phase timing / profiling.

Reference parity: ``DGraph/utils/TimingReport.py:19-84`` (static timer
registry; start/stop wrap CUDA events with communicator barriers; context
manager form; add_time; JSON-able report) and the module-global TIMINGS dict
(``NCCLBackendEngine.py:32``).

TPU-first: there are no CUDA events; accurate device timing comes from
``jax.block_until_ready`` around host timers (what ``stop`` does here), and
deep profiling from ``jax.profiler.trace`` (Perfetto), which
:func:`trace_to` wraps. ``jax.named_scope`` replaces nvtx.annotate
(``microbenchmark_graphcast.py:126``).
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict
from typing import Optional

import jax


class TimingReport:
    """Static-registry phase timer (same surface as the reference's)."""

    _starts: dict = {}
    _times: dict = defaultdict(list)

    @classmethod
    def start(cls, name: str) -> None:
        cls._starts[name] = time.perf_counter()

    @classmethod
    def stop(cls, name: str, sync: Optional[object] = None) -> float:
        """Stop the timer; if ``sync`` is a jax array (or pytree), blocks on
        it first so the interval covers device execution (the CUDA-event
        synchronize analogue)."""
        if sync is not None:
            jax.block_until_ready(sync)
        dt = (time.perf_counter() - cls._starts.pop(name)) * 1000.0
        cls._times[name].append(dt)
        return dt

    @classmethod
    @contextlib.contextmanager
    def time(cls, name: str):
        """Context form; set ``result["sync"]`` to a jax value to make the
        stop block on device completion."""
        cls.start(name)
        result = {}
        try:
            yield result
        finally:
            cls.stop(name, sync=result.get("sync"))

    @classmethod
    def add_time(cls, name: str, ms: float) -> None:
        cls._times[name].append(ms)

    @classmethod
    def report(cls) -> dict:
        """name -> {mean, std, count, total} in ms."""
        import numpy as np

        out = {}
        for k, v in cls._times.items():
            a = np.asarray(v)
            out[k] = {
                "mean_ms": float(a.mean()),
                "std_ms": float(a.std()),
                "count": len(v),
                "total_ms": float(a.sum()),
            }
        return out

    @classmethod
    def dump_json(cls, path: str) -> None:
        with open(path, "w") as f:
            json.dump(cls.report(), f, indent=2)

    @classmethod
    def reset(cls) -> None:
        cls._starts.clear()
        cls._times.clear()


@contextlib.contextmanager
def trace_to(logdir: str):
    """Perfetto/TensorBoard trace of the enclosed block (the torch.profiler
    analogue, ``train_graphcast.py:161-169``)."""
    with jax.profiler.trace(logdir):
        yield


named_scope = jax.named_scope


def salt_input(a, salt):
    """Fold a scan-carry scalar into an op input with no meaningful value
    change: ``a + cast(salt * 1e-20)`` keeps a LIVE data dependence on the
    loop carry so scan iterations serialize and XLA cannot hoist the op
    out of the timing loop. The scale makes the perturbation ~1e-18 on
    O(1) inputs — numerically invisible — and the cast avoids promoting
    bf16 inputs to the f32 carry dtype (which would silently benchmark
    f32 kernels).

    Previously ``cast(salt) * 0``: XLA's simplifier folded that to a
    constant despite float NaN/Inf semantics, severed the chain, and
    loop-invariant code motion hoisted the op — producing impossible
    ~0 ms "measurements" (caught in r3 via a 0.011 ms 240k-row gather).

    FLOAT inputs only: for integer dtypes the 1e-20 scale would cast to
    exactly 0 and silently reopen the hole, so that's a hard error.
    """
    import jax.numpy as jnp

    if not jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
        raise TypeError(
            f"salt_input needs a float array (got {jnp.asarray(a).dtype}): "
            f"an integer cast of salt*1e-20 is exactly 0, which severs the "
            f"loop-carried dependence the hoist-proofing relies on")
    return a + (salt * 1e-20).astype(a.dtype)


def timed_scan_ms(fn, *, reps: int = 3, n_long: int = 8):
    """Best positive (long - short) / (n_long - 1) delta in ms for one op.

    The single-chip timing protocol (see bench.py's rationale): on the
    tunneled TPU ``block_until_ready`` is not a reliable completion barrier
    and identical dispatches can be memoized, so run the op n times INSIDE
    one jit via ``lax.scan`` with a scalar carry fetched to host, and
    subtract a 1-iteration run so per-call RPC latency cancels.

    ``fn(salt)`` must return an array and fold ``salt`` (f32 scalar) into
    its inputs via :func:`salt_input`. Returns None if no rep produced a
    positive delta (wedged/noisy tunnel).
    """
    import functools
    import time as _time

    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames="n")
    def loop(s, n):
        def body(acc, _):
            out = fn(acc)
            # consume the WHOLE output: a single-element fetch
            # (out.ravel()[0]) lets XLA slice through sliceable ops —
            # a row gather collapses to gathering ONE row and the
            # "measurement" is ~0 (caught in r3: a 9 TB/s CPU gather).
            # The sum can still fuse into the producer (output writes may
            # be elided), but every input byte is genuinely read.
            return acc + out.astype(jnp.float32).sum() * 1e-20, None

        acc, _ = jax.lax.scan(body, s, None, length=n)
        return acc

    float(loop(jnp.float32(0), 1))
    float(loop(jnp.float32(0), n_long))
    best = None
    for r in range(reps):
        # DISTINCT carry per dispatch: value-identical dispatches are the
        # memoization case this whole protocol exists to avoid
        t0 = _time.perf_counter(); float(loop(jnp.float32(r + 1), 1))
        t1 = _time.perf_counter() - t0
        t0 = _time.perf_counter(); float(loop(jnp.float32(r + 101), n_long))
        tl = _time.perf_counter() - t0
        d = (tl - t1) / (n_long - 1) * 1000.0
        if d > 0 and (best is None or d < best):
            best = d
    return best
