"""Wire-format specs: WHAT encoding halo payloads ride the wire in.

The codec layer separates *which rows cross the wire* (the plan's halo
send tables and the compiled :mod:`dgraph_tpu.sched` rounds) from *how
they are encoded*. This module is the format side: a registry of
serializable :class:`WireFormat` specs, the resolution ladder that
decides which one a run adopts, byte pricing (what ``obs.footprint``
and the trace/HLO byte pins charge per row), and numpy reference codecs
that are the ground truth the jax codecs
(:mod:`dgraph_tpu.wire.codec`) are tested against.

Formats:

- ``fp32`` — the identity default: the payload rides the wire in the
  activation dtype, exactly today's path (a bf16-compute program ships
  bf16; the codec layer adds NOTHING — bit-identical end to end).
- ``bf16`` — payload cast to bfloat16 on send, accumulated back at the
  receiver's dtype through f32-exact widening. Halves the wire bytes of
  an f32 program; lossless when the activations are already bf16.
- ``fp8``  — scaled float8 e4m3 with a per-row max-abs scale: each
  ``[F]`` row is divided by ``max|x| / 448`` and cast to e4m3; the f32
  scale is bitcast into 4 trailing uint8 lanes of the SAME payload row,
  so the wire operand is one ``[.., F+4]`` uint8 array (one collective,
  one byte-exact operand to pin — no scale side channel).

Error compensation (opt-in): :func:`np_encode_compensated` carries the
encode residual forward so the values a receiver accumulates over many
steps stay within a pinned tolerance of fp32 — the classic
error-feedback trick, exposed at the codec level for training loops
that thread residual state.

Contracts (mirrors :mod:`dgraph_tpu.sched.ir`):

- **jax-free** (``analysis.lint``'s ``jax-free-module`` rule): specs,
  pricing, the resolution ladder, and the selftest codecs must load and
  run on a host where jax is wedged or absent.
- **Hashable + serializable**: :class:`WireFormat` is a frozen
  dataclass of primitives; the format NAME rides
  :class:`~dgraph_tpu.plan.EdgePlan` static aux and tuning records, and
  ``format_id`` is a content hash so two holders of the same id
  provably price the same bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging

import numpy as np

_logger = logging.getLogger("dgraph_tpu.wire")

# Bump when a serialized field changes meaning (additive fields do not).
WIRE_FORMAT_VERSION = 1

# Largest finite float8 e4m3fn magnitude: per-row scales normalize the
# row's max-abs to exactly this, so the quantizer never saturates.
E4M3_MAX = 448.0

# f32 bytes of the per-row scale the fp8 codec bitcasts into trailing
# uint8 payload lanes (the "+4" of its priced row width).
FP8_SCALE_BYTES = 4


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """One wire encoding for halo payload rows.

    ``payload_itemsize`` is the encoded per-feature byte width
    (``None`` = identity: the payload rides the activation dtype);
    ``row_overhead_bytes`` is packed INTO the payload row (the fp8
    scale lanes), so a format's whole wire cost is one operand.
    """

    name: str
    wire_dtype: str  # numpy-style dtype name of the wire operand
    payload_itemsize: "int | None"  # None = activation dtype (identity)
    row_overhead_bytes: int = 0
    scaled: bool = False  # per-row max-abs scale carried in the payload
    lossless_from: tuple = ()  # activation dtypes round-tripped exactly
    description: str = ""

    def wire_row_bytes(self, feat_dim: int, activation_itemsize: int) -> int:
        """Bytes ONE encoded feature row occupies on the wire — the
        number every pricer (footprint, tuner) and every pin (trace,
        HLO) must agree on."""
        if self.payload_itemsize is None:
            return int(feat_dim) * int(activation_itemsize)
        return int(feat_dim) * self.payload_itemsize + self.row_overhead_bytes

    def wire_feat_dim(self, feat_dim: int) -> int:
        """Last-axis length of the encoded operand (the fp8 payload
        widens by its packed scale lanes)."""
        if self.payload_itemsize is None:
            return int(feat_dim)
        return int(feat_dim) + self.row_overhead_bytes // max(
            self.payload_itemsize, 1
        )

    def compression_ratio(self, feat_dim: int, activation_itemsize: int) -> float:
        """activation-row bytes / wire-row bytes (1.0 = identity)."""
        raw = int(feat_dim) * int(activation_itemsize)
        wire = self.wire_row_bytes(feat_dim, activation_itemsize)
        return raw / wire if wire else 1.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["lossless_from"] = list(self.lossless_from)
        d["version"] = WIRE_FORMAT_VERSION
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "WireFormat":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        kw["lossless_from"] = tuple(kw.get("lossless_from", ()))
        return cls(**kw)

    @property
    def format_id(self) -> str:
        """Content hash of the canonical serialization (the
        ``schedule_id`` convention): equal ids imply equal pricing."""
        key = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha1(key.encode()).hexdigest()[:12]


WIRE_FORMATS = {
    "fp32": WireFormat(
        name="fp32", wire_dtype="", payload_itemsize=None,
        lossless_from=("float32", "bfloat16", "float16"),
        description="identity: payload rides the activation dtype "
        "(bit-identical to the pre-codec wire)",
    ),
    "bf16": WireFormat(
        name="bf16", wire_dtype="bfloat16", payload_itemsize=2,
        lossless_from=("bfloat16",),
        description="bfloat16 payload, f32-exact widening on receive",
    ),
    "fp8": WireFormat(
        name="fp8", wire_dtype="uint8", payload_itemsize=1,
        row_overhead_bytes=FP8_SCALE_BYTES, scaled=True,
        description="float8 e4m3 payload with a per-row max-abs f32 "
        "scale packed into 4 trailing uint8 lanes",
    ),
}

WIRE_FORMAT_NAMES = tuple(WIRE_FORMATS)


def get_format(name: str) -> WireFormat:
    try:
        return WIRE_FORMATS[name]
    except KeyError:
        raise ValueError(
            f"unknown wire format {name!r}; known: {WIRE_FORMAT_NAMES}"
        ) from None


def fp8_available() -> bool:
    """Can the fp8 codec encode here? ml_dtypes ships with jax's own
    dependency set, but the gate stays explicit: a host without it must
    degrade with one warning, never crash at trace time."""
    try:
        import ml_dtypes  # noqa: F401

        np.dtype(ml_dtypes.float8_e4m3fn)
        return True
    except Exception:  # noqa: BLE001 — any import/dtype wedge = absent
        return False


_degrade_warned: set = set()


def _warn_degrade(name: str, source: str, why: str) -> None:
    key = (name, source, why)
    if key in _degrade_warned:
        return
    _degrade_warned.add(key)
    _logger.warning(
        "wire_format=%r requested by %s but %s; the next resolution "
        "tier decides the format instead", name, source, why,
    )


def resolve_wire_format(
    world_size: int,
    halo_deltas: tuple,
    *,
    plan_format: str = "fp32",
    fp8_ok: "bool | None" = None,
) -> tuple:
    """The wire format a run will actually encode with, plus who decided.

    The exact ladder shape of :func:`dgraph_tpu.plan.resolve_halo_impl`:

    - ``'env'``     — ``DGRAPH_TPU_WIRE_FORMAT`` / ``config.set_flags``
      pins the format ('auto' defers).
    - ``'record'``  — an adopted TuningRecord chose it
      (``config.tuned_wire_format``).
    - ``'plan'``    — the format attached to the plan at build time
      (``EdgePlan.wire_format`` — itself the build-time resolution, so
      a cache round-trip keeps the adopted format).
    - ``'default'`` — nothing chose: the fp32 identity format (a lossy
      codec never engages on its own — the un-A/B'd-kernel discipline).

    A tier naming a format whose preconditions fail (``fp8`` without the
    e4m3 dtype, an unknown name) degrades with ONE warning to the next
    tier — never a silent wrong answer. Plans with no cross-rank traffic
    resolve ``('fp32', 'plan')``: there is no wire to encode.
    """
    from dgraph_tpu import config as _cfg

    if not halo_deltas:
        return "fp32", "plan"

    def _ok(name: str, source: str) -> bool:
        if name not in WIRE_FORMATS:
            _warn_degrade(name, source, f"it is not a registered format "
                          f"(known: {WIRE_FORMAT_NAMES})")
            return False
        if name == "fp8":
            avail = fp8_ok if fp8_ok is not None else fp8_available()
            if not avail:
                _warn_degrade(name, source,
                              "the float8 e4m3 dtype is unavailable here")
                return False
        return True

    env = getattr(_cfg, "wire_format", "auto")
    tuned = getattr(_cfg, "tuned_wire_format", None)
    for name, source in ((env, "env"), (tuned, "record"),
                         (plan_format, "plan")):
        if name in (None, "", "auto"):
            continue
        if name == "fp32" and source == "plan":
            # the attached default is not an adoption — fall through so
            # the source reports 'default' (nothing chose)
            break
        if _ok(name, source):
            return name, source
    return "fp32", "default"


# ---------------------------------------------------------------------------
# numpy reference codecs — ground truth for the jax pair, and what the
# compile-free selftest (wire/__main__.py) runs its vacuity mutants on
# ---------------------------------------------------------------------------


def _bf16_np():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _fp8_np():
    import ml_dtypes

    return np.dtype(ml_dtypes.float8_e4m3fn)


def np_encode(x: np.ndarray, fmt: "WireFormat | str",
              *, _scale_gain: float = 1.0) -> np.ndarray:
    """Reference encode of ``[.., F]`` rows to the wire operand.
    ``_scale_gain`` exists ONLY for the selftest's wrong-scale vacuity
    mutant (a codec whose decode disagrees with its encode scale must
    blow the round-trip bound, proving the bound can go RED)."""
    fmt = get_format(fmt) if isinstance(fmt, str) else fmt
    x = np.asarray(x)
    if fmt.payload_itemsize is None:  # fp32 identity
        return x
    if fmt.name == "bf16":
        return x.astype(_bf16_np())
    if fmt.name == "fp8":
        x32 = np.ascontiguousarray(x, dtype=np.float32)
        amax = np.max(np.abs(x32), axis=-1, keepdims=True)
        scale = np.where(amax > 0, amax / E4M3_MAX, np.float32(1.0))
        scale = scale.astype(np.float32)
        q = (x32 / (scale * _scale_gain)).astype(_fp8_np())
        payload = q.view(np.uint8)
        scale_lanes = np.ascontiguousarray(scale).view(np.uint8)
        return np.concatenate(
            [payload, scale_lanes.reshape(scale.shape[:-1] + (4,))], axis=-1
        )
    raise ValueError(f"no reference encoder for format {fmt.name!r}")


def np_decode(y: np.ndarray, fmt: "WireFormat | str",
              out_dtype=np.float32) -> np.ndarray:
    """Reference decode back to ``out_dtype`` (accumulation happens at
    f32: both lossy payloads widen exactly into f32 before any cast)."""
    fmt = get_format(fmt) if isinstance(fmt, str) else fmt
    y = np.asarray(y)
    if fmt.payload_itemsize is None:
        return y.astype(out_dtype) if y.dtype != out_dtype else y
    if fmt.name == "bf16":
        return y.astype(np.float32).astype(out_dtype)
    if fmt.name == "fp8":
        F = y.shape[-1] - FP8_SCALE_BYTES
        payload = np.ascontiguousarray(y[..., :F]).view(_fp8_np())
        scale = np.ascontiguousarray(y[..., F:]).view(np.float32)
        return (payload.astype(np.float32) * scale).astype(out_dtype)
    raise ValueError(f"no reference decoder for format {fmt.name!r}")


def np_roundtrip_bound(fmt: "WireFormat | str") -> float:
    """Pinned max relative row-wise error of one encode/decode trip:
    0 for identity, one ulp of the payload mantissa for the casts
    (bf16: 8 mantissa bits; e4m3: 3 bits, plus per-row scale rounding)."""
    fmt = get_format(fmt) if isinstance(fmt, str) else fmt
    return {"fp32": 0.0, "bf16": 2.0 ** -8, "fp8": 2.0 ** -3.5}[fmt.name]


def np_encode_compensated(
    x: np.ndarray, resid: "np.ndarray | None", fmt: "WireFormat | str",
    *, _drop_residual: bool = False,
) -> tuple:
    """Error-feedback encode: quantize ``x + resid`` and carry what the
    wire lost forward, so the RECEIVER'S ACCUMULATION over steps tracks
    the fp32 sum within a pinned bound instead of drifting with step
    count. Returns ``(wire_payload, new_resid)``; thread ``new_resid``
    into the next step's call (``resid=None`` starts at zero).
    ``_drop_residual`` is the selftest's dropped-residual vacuity mutant
    (compensation that doesn't carry must drift past the pinned bound)."""
    fmt = get_format(fmt) if isinstance(fmt, str) else fmt
    x32 = np.asarray(x, dtype=np.float32)
    carried = x32 if resid is None else x32 + np.asarray(resid, np.float32)
    y = np_encode(carried, fmt)
    if _drop_residual:
        return y, np.zeros_like(x32)
    return y, carried - np_decode(y, fmt, np.float32)


# ---------------------------------------------------------------------------
# delta-skip accounting: what the n_deltas-aware schedules save
# ---------------------------------------------------------------------------


def delta_skip_rows(pair_rows, world_size: int, s_pad: int) -> dict:
    """Row accounting of shipping ONLY live rows (the compiled
    schedule's per-pair heights) versus the dense lowerings' padded
    operands — the delta-skip generalization, as numbers: the ``sched``
    lowering already ships ~``live_rows`` per shard where ``all_to_all``
    ships ``(W-1) * s_pad`` and a ppermute ring ``n_deltas * s_pad``."""
    rows = tuple(tuple(int(v) for v in r) for r in pair_rows)
    live = sum(v for r in rows for v in r)
    deltas = sorted({
        (d - s) % world_size
        for s, r in enumerate(rows) for d, v in enumerate(r) if v and s != d
    })
    return {
        "live_rows_total": live,
        "a2a_rows_per_shard": (world_size - 1) * int(s_pad),
        "ppermute_rows_per_shard": len(deltas) * int(s_pad),
        "live_rows_max_shard": max(
            (sum(r) for r in rows), default=0
        ),
        "num_halo_deltas": len(deltas),
    }
