"""Wire codec layer: compressed halo payloads as verified, tuner-ranked
wire formats (ROADMAP item 1 — attack the dominant halo wire bytes).

Separates *what rows cross the wire* (the plan's send tables, the sched
compiler's rounds) from *how they are encoded*: a registry of
serializable :class:`~dgraph_tpu.wire.spec.WireFormat` specs (fp32
identity / bf16 / scaled fp8-e4m3), a resolution ladder mirroring
``resolve_halo_impl``, hub-row dedup with a delivery-simulation
verifier, and jax codecs whose custom-VJP pairs encode cotangents with
the same format.

The spec, pricing, resolver, and dedup modules are jax-free by the
lint-enforced contract; the jax codecs live in
:mod:`dgraph_tpu.wire.codec` and are re-exported lazily below (PEP 562)
so jax-free consumers importing ``dgraph_tpu.wire.spec`` never pay the
jax import.
"""

from dgraph_tpu.wire.dedup import (
    DedupPlan,
    HubRow,
    RelayTransfer,
    build_dedup_plan,
    dedup_stats,
    detect_hub_rows,
    pair_live_rows,
    verify_dedup_coverage,
)
from dgraph_tpu.wire.spec import (
    E4M3_MAX,
    FP8_SCALE_BYTES,
    WIRE_FORMAT_NAMES,
    WIRE_FORMAT_VERSION,
    WIRE_FORMATS,
    WireFormat,
    delta_skip_rows,
    fp8_available,
    get_format,
    np_decode,
    np_encode,
    np_encode_compensated,
    np_roundtrip_bound,
    resolve_wire_format,
)

_CODEC_EXPORTS = (
    "encode_compensated",
    "fp8_jnp_ok",
    "make_a2a_codec",
    "make_ppermute_codec",
    "make_wire_codec",
    "make_wire_transform",
)


def __getattr__(name):  # PEP 562: jax loads only when a codec is asked for
    if name in _CODEC_EXPORTS:
        from dgraph_tpu.wire import codec

        return getattr(codec, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "DedupPlan",
    "E4M3_MAX",
    "FP8_SCALE_BYTES",
    "HubRow",
    "RelayTransfer",
    "WIRE_FORMATS",
    "WIRE_FORMAT_NAMES",
    "WIRE_FORMAT_VERSION",
    "WireFormat",
    "build_dedup_plan",
    "dedup_stats",
    "delta_skip_rows",
    "detect_hub_rows",
    "fp8_available",
    "get_format",
    "np_decode",
    "np_encode",
    "np_encode_compensated",
    "np_roundtrip_bound",
    "pair_live_rows",
    "resolve_wire_format",
    "verify_dedup_coverage",
    *_CODEC_EXPORTS,
]
