"""Wire-codec selftest CLI (compile-free, jax-free).

``python -m dgraph_tpu.wire --selftest true`` proves on fixed fixtures,
with zero XLA compiles and without importing jax:

- registry integrity: WireFormat to_dict -> JSON -> from_dict is
  identity, ``format_id`` stable across the trip, and the priced
  ``wire_row_bytes`` pins hold (fp32 F*4, bf16 F*2, fp8 F+4 — the exact
  numbers obs.footprint charges and the trace/HLO tiers pin);
- numpy reference codecs: every format round-trips within its pinned
  :func:`~dgraph_tpu.wire.spec.np_roundtrip_bound`, fp32 is the
  identity, and an all-zero fp8 wire row decodes to exactly 0.0 (the
  value ppermute hands non-receivers);
- error compensation: the residual-carry telescopes, so T steps of
  compensated encode drift by at most ONE step's quantization error
  (T-independent) where the uncompensated stream drifts linearly in T;
- the resolution ladder: env pin > tuned record > plan-attached >
  fp32 default, with precondition failures (fp8 without e4m3, unknown
  names) degrading to the next tier;
- hub-row dedup: the fixture plan verifies delivery-exact, and the
  vacuity mutants — wrong fp8 scale, dropped compensation residual,
  duplicated relay (double-count), dropped needer, non-causal carrier —
  must each go RED. A verifier that cannot fail proves nothing.

Wired as the ``wire-selftest`` pass in ``scripts/check.py``.
"""

from __future__ import annotations

import dataclasses
import json
import sys

import numpy as np

from dgraph_tpu.wire.dedup import (
    RelayTransfer,
    build_dedup_plan,
    dedup_stats,
    detect_hub_rows,
    verify_dedup_coverage,
)
from dgraph_tpu.wire.spec import (
    WIRE_FORMATS,
    WireFormat,
    delta_skip_rows,
    np_decode,
    np_encode,
    np_encode_compensated,
    np_roundtrip_bound,
    resolve_wire_format,
)


def _dedup_fixture():
    """4-rank world, s_pad=4: src 0's row 5 is a hub needed by ranks
    1, 2 and 3 (primary 1); everything else is plain pair traffic."""
    W, S = 4, 4
    idx = np.zeros((W, W, S), dtype=np.int32)
    msk = np.zeros((W, W, S), dtype=np.int32)

    def block(s, d, rows):
        for k, r in enumerate(rows):
            idx[s, d, k] = r
            msk[s, d, k] = 1

    block(0, 1, [5, 6])
    block(0, 2, [5])
    block(0, 3, [5, 9])
    block(1, 0, [3])
    block(2, 3, [4, 8])
    block(3, 2, [2, 5])
    return idx, msk, S


def _selftest() -> dict:
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    jax_preloaded = "jax" in sys.modules
    rng = np.random.default_rng(0)

    # --- registry + pricing pins ------------------------------------
    F, f32_size = 128, 4
    pins = {"fp32": F * 4, "bf16": F * 2, "fp8": F + 4}
    for name, fmt in WIRE_FORMATS.items():
        wire = json.loads(json.dumps(fmt.to_dict()))
        back = WireFormat.from_dict(wire)
        check(back == fmt, f"{name}: JSON round-trip lost structure")
        check(back.format_id == fmt.format_id,
              f"{name}: format_id unstable across round-trip")
        check(fmt.wire_row_bytes(F, f32_size) == pins[name],
              f"{name}: wire_row_bytes {fmt.wire_row_bytes(F, f32_size)} "
              f"!= pinned {pins[name]}")
    check(WIRE_FORMATS["bf16"].compression_ratio(F, f32_size) == 2.0,
          "bf16 must halve f32 wire rows (the >=45% acceptance cut)")
    # the identity format prices at the ACTIVATION itemsize: a bf16
    # program's fp32-format wire is already 2-byte rows
    check(WIRE_FORMATS["fp32"].wire_row_bytes(F, 2) == F * 2,
          "fp32 identity must price at the activation itemsize")

    # --- numpy codec round-trips ------------------------------------
    x = rng.standard_normal((6, 16)).astype(np.float32)
    x[2] *= 1e3  # large-magnitude row exercises the per-row scale
    x[4] = 0.0   # all-zero (masked) row must survive exactly
    for name in WIRE_FORMATS:
        y = np_encode(x, name)
        z = np_decode(y, name)
        bound = np_roundtrip_bound(name)
        rowmax = np.max(np.abs(x), axis=-1, keepdims=True)
        err = np.max(np.abs(z - x), axis=-1, keepdims=True)
        check(bool(np.all(err <= bound * rowmax + 1e-12)),
              f"{name}: round-trip error exceeds pinned bound {bound}")
        if name == "fp32":
            check(z is x or bool(np.array_equal(z, x)),
                  "fp32 must be the bit-identity")
        if name == "fp8":
            check(y.dtype == np.uint8 and y.shape == (6, 20),
                  "fp8 wire operand must be one [.., F+4] uint8 array")
            check(bool(np.all(np_decode(np.zeros_like(y), name) == 0.0)),
                  "all-zero fp8 wire row must decode to exactly 0.0")

    # vacuity: a codec whose decode disagrees with its encode scale must
    # blow the bound — otherwise the bound proves nothing
    y_bad = np_encode(x, "fp8", _scale_gain=2.0)
    err_bad = np.max(np.abs(np_decode(y_bad, "fp8") - x))
    check(err_bad > np_roundtrip_bound("fp8") * float(np.max(np.abs(x))),
          "vacuity: wrong-scale fp8 mutant stayed inside the bound")

    # --- compensated mode: drift is T-independent --------------------
    T = 64
    v = rng.standard_normal((3, 16)).astype(np.float32)
    for name in ("fp8", "bf16"):
        bound = np_roundtrip_bound(name)
        rowmax = float(np.max(np.abs(v)))
        acc, acc_drop = np.zeros_like(v), np.zeros_like(v)
        resid = None
        for _ in range(T):
            y, resid = np_encode_compensated(v, resid, name)
            acc += np_decode(y, name)
            y_drop, _ = np_encode_compensated(v, None, name,
                                              _drop_residual=True)
            acc_drop += np_decode(y_drop, name)
        drift = float(np.max(np.abs(acc - T * v)))
        drift_drop = float(np.max(np.abs(acc_drop - T * v)))
        check(drift <= 2.0 * bound * rowmax,
              f"{name}: compensated drift {drift:.4g} exceeds the "
              f"one-step pin {2.0 * bound * rowmax:.4g} after {T} steps")
        check(drift_drop > 4.0 * bound * rowmax,
              f"vacuity: {name} dropped-residual mutant did not drift "
              f"(compensation test proves nothing)")
        check(drift < drift_drop,
              f"{name}: compensation did not beat the uncompensated "
              f"stream")

    # --- resolution ladder ------------------------------------------
    from dgraph_tpu import config as _cfg

    saved = (_cfg.wire_format, _cfg.tuned_wire_format)
    deltas = (1, 3)
    try:
        for env, tuned, plan, fp8_ok, want in (
            ("bf16", None, "fp32", True, ("bf16", "env")),
            ("auto", "fp8", "fp32", True, ("fp8", "record")),
            ("auto", None, "bf16", True, ("bf16", "plan")),
            ("auto", None, "fp32", True, ("fp32", "default")),
            # precondition failure degrades to the next tier
            ("fp8", "bf16", "fp32", False, ("bf16", "record")),
            ("not-a-format", None, "bf16", True, ("bf16", "plan")),
        ):
            _cfg.set_flags(wire_format=env, tuned_wire_format=tuned)
            got = resolve_wire_format(4, deltas, plan_format=plan,
                                      fp8_ok=fp8_ok)
            check(got == want,
                  f"ladder(env={env}, tuned={tuned}, plan={plan}, "
                  f"fp8_ok={fp8_ok}) -> {got}, want {want}")
        # no cross-rank traffic: nothing rides a wire, format is moot
        _cfg.set_flags(wire_format="fp8", tuned_wire_format=None)
        check(resolve_wire_format(1, ()) == ("fp32", "plan"),
              "empty-deltas plan must resolve ('fp32', 'plan')")
    finally:
        _cfg.set_flags(wire_format=saved[0], tuned_wire_format=saved[1])

    # --- hub-row dedup ----------------------------------------------
    idx, msk, s_pad = _dedup_fixture()
    hubs = detect_hub_rows(idx, msk)
    check(len(hubs) == 1 and hubs[0].src == 0 and hubs[0].row == 5
          and hubs[0].needers == (1, 2, 3),
          f"hub detection wrong: {hubs}")
    plan = build_dedup_plan(idx, msk, s_pad=s_pad)
    check(verify_dedup_coverage(plan, idx, msk) == [],
          "dedup fixture plan fails its own delivery verifier")
    stats = dedup_stats(plan, idx, msk)
    check(stats["owner_egress_rows_saved"] == 2,
          f"hub with 3 needers must save 2 owner-egress rows: {stats}")
    check(stats["relay_rows"] == 2 and stats["relay_rounds"] == 2,
          f"recursive-doubling fan-out of 3 needers is 2 relays: {stats}")
    check(stats["max_rank_egress_after"]
          <= stats["max_rank_egress_before"],
          f"dedup must not worsen the bottleneck egress: {stats}")

    # vacuity mutants against the delivery verifier
    dup = dataclasses.replace(plan, relay_rounds=plan.relay_rounds + (
        (RelayTransfer(carrier=1, dst=2, src=0, row=5),),))
    check(any("delivered 2 times" in f
              for f in verify_dedup_coverage(dup, idx, msk)),
          "vacuity: duplicated relay (double-count) not flagged RED")
    dropped = dataclasses.replace(plan,
                                  relay_rounds=plan.relay_rounds[:1])
    check(any("never delivered" in f
              for f in verify_dedup_coverage(dropped, idx, msk)),
          "vacuity: dropped needer not flagged RED")
    noncausal = dataclasses.replace(plan, relay_rounds=(
        (RelayTransfer(carrier=2, dst=3, src=0, row=5),),
        (RelayTransfer(carrier=1, dst=2, src=0, row=5),),))
    check(any("does not hold" in f
              for f in verify_dedup_coverage(noncausal, idx, msk)),
          "vacuity: non-causal relay carrier not flagged RED")

    # --- delta-skip accounting ---------------------------------------
    rows = ((0, 64, 1, 2), (1, 0, 1, 0), (2, 1, 0, 1), (0, 2, 1, 0))
    ds = delta_skip_rows(rows, world_size=4, s_pad=64)
    check(ds["live_rows_total"] == sum(v for r in rows for v in r),
          f"delta-skip live-row accounting wrong: {ds}")
    check(ds["a2a_rows_per_shard"] == 3 * 64
          and ds["live_rows_total"] < 4 * ds["a2a_rows_per_shard"],
          f"delta-skip must price the dense a2a baseline: {ds}")

    if not jax_preloaded:
        check("jax" not in sys.modules,
              "selftest imported jax — wire spec/dedup are not jax-free")

    return {"kind": "wire_selftest", "formats": sorted(WIRE_FORMATS),
            "failures": failures, "ok": not failures}


@dataclasses.dataclass
class Config:
    """Wire-codec CLI: ``--selftest true`` runs the compile-free codec
    + resolver + dedup invariant and vacuity-mutant suite; exit 1 on
    any failure."""

    selftest: bool = False
    indent: int = 0


def main(cfg: Config) -> None:
    if not cfg.selftest:
        print(__doc__)
        return
    out = _selftest()
    print(json.dumps(out, indent=cfg.indent or None))
    if out["failures"]:
        raise SystemExit(1)


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
