"""jax wire codecs: encode/decode pairs + custom-VJP wire-trip wrappers.

The jax side of :mod:`dgraph_tpu.wire.spec` (whose numpy codecs are the
ground truth these are tested against). Three layers, all
``lru_cache``'d factories keyed by static (format, dtype) so jit tracing
sees one stable callable per configuration:

- :func:`make_wire_transform` — the raw ``(encode, decode)`` jnp
  functions (``(None, None)`` for the fp32 identity format, so the
  caller's fp32 code path is LITERALLY unchanged — the bit-identity
  guarantee is structural, not numerical).
- :func:`make_wire_codec` — the custom-VJP pair: ``encode``'s bwd
  decodes the cotangent, ``decode``'s bwd encodes it, so a cotangent
  crossing the wire rides it in the SAME format as the forward payload
  and AD never differentiates through the cast.
- :func:`make_a2a_codec` / :func:`make_ppermute_codec` — whole wire
  trips (encode -> collective -> decode) under ONE custom_vjp. These
  exist because the fp8 payload is a uint8 operand: an integer
  intermediate has no tangent space, so plain AD through
  ``all_to_all(encode(x))`` would silently drop the gradient. Wrapping
  the trip makes the integer hop invisible to AD while the hand-written
  bwd encodes the cotangent and rides the transposed collective
  (``all_to_all(split=0, concat=0)`` is its own transpose; a ppermute's
  transpose is the inverted permutation).

The multi-round executors in ``comm.collectives`` (overlap / pallas_p2p
/ sched) are ALREADY custom-VJP bodies — opaque to AD — so they call the
raw transforms directly and encode their hand-built cotangent legs with
the same pair.

fp8 packing (must match :func:`dgraph_tpu.wire.spec.np_encode` bit for
bit): per-row scale ``max|x| / 448`` (zero rows scale 1.0), payload
``(x/scale) -> e4m3 -> bitcast uint8``, the f32 scale bitcast into 4
trailing uint8 lanes of the same ``[.., F+4]`` operand — one collective,
one priced operand. An all-zero wire row (ppermute's zeros at
non-receivers, p2p's untouched buffer tail) decodes to exactly 0.0
because both its payload and its scale lanes are zero bytes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dgraph_tpu.wire.spec import E4M3_MAX, FP8_SCALE_BYTES, get_format


def fp8_jnp_ok() -> bool:
    """Does this jax build expose the e4m3 dtype? (Tracks
    :func:`dgraph_tpu.wire.spec.fp8_available`, which gates the
    resolution ladder on the jax-free ml_dtypes probe.)"""
    try:
        jnp.dtype(jnp.float8_e4m3fn)
        return True
    except Exception:  # noqa: BLE001 — absent attr or wedged backend
        return False


def _fp8_encode(x, dtype_name: str):
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / E4M3_MAX, jnp.float32(1.0))
    scale = scale.astype(jnp.float32)
    q = (x32 / scale).astype(jnp.float8_e4m3fn)
    payload = lax.bitcast_convert_type(q, jnp.uint8)
    lanes = lax.bitcast_convert_type(scale, jnp.uint8)  # [.., 1] -> [.., 1, 4]
    lanes = lanes.reshape(scale.shape[:-1] + (FP8_SCALE_BYTES,))
    return jnp.concatenate([payload, lanes], axis=-1)


def _fp8_decode(y, dtype_name: str):
    F = y.shape[-1] - FP8_SCALE_BYTES
    payload = lax.bitcast_convert_type(y[..., :F], jnp.float8_e4m3fn)
    scale = lax.bitcast_convert_type(
        y[..., F:].reshape(y.shape[:-1] + (1, FP8_SCALE_BYTES)), jnp.float32
    )
    return (payload.astype(jnp.float32) * scale).astype(dtype_name)


@functools.lru_cache(maxsize=None)
def make_wire_transform(fmt_name: str, dtype_name: str):
    """Raw ``(encode, decode)`` for activation dtype ``dtype_name``, or
    ``(None, None)`` when the format is the identity (fp32 — and any
    format whose wire dtype already equals the activation dtype, where
    inserting casts would be pure noise in the lowered module)."""
    fmt = get_format(fmt_name)
    if fmt.payload_itemsize is None:
        return None, None
    if fmt.name == "bf16":
        if dtype_name == "bfloat16":
            return None, None  # activations already ride the wire dtype

        def enc(x):
            return x.astype(jnp.bfloat16)

        def dec(y):
            return y.astype(jnp.float32).astype(dtype_name)

        return enc, dec
    if fmt.name == "fp8":
        if not fp8_jnp_ok():
            raise RuntimeError(
                "wire format 'fp8' requires the float8_e4m3fn dtype; "
                "resolve_wire_format should have degraded before tracing"
            )
        return (functools.partial(_fp8_encode, dtype_name=dtype_name),
                functools.partial(_fp8_decode, dtype_name=dtype_name))
    raise ValueError(f"no jax codec for wire format {fmt_name!r}")


@functools.lru_cache(maxsize=None)
def make_wire_codec(fmt_name: str, dtype_name: str):
    """The custom-VJP ``(encode, decode)`` pair: each side's bwd applies
    the opposite raw transform, so cotangents ride the wire encoded with
    the same format.

    Float wire dtypes (bf16) compose through plain-AD collectives.
    Integer-payload formats (fp8) are returned as the RAW transforms:
    a uint8 primal has no tangent space, so a standalone custom-VJP pair
    could never hand its bwd a usable cotangent — fp8 is only legal
    inside already-opaque custom-VJP bodies (the multi-round executors)
    or the wire-trip wrappers below, where AD never meets the integer
    intermediate.
    """
    enc_raw, dec_raw = make_wire_transform(fmt_name, dtype_name)
    if enc_raw is None:
        return None, None
    fmt = get_format(fmt_name)
    if fmt.wire_dtype == "uint8":
        return enc_raw, dec_raw

    @jax.custom_vjp
    def encode(x):
        return enc_raw(x)

    encode.defvjp(lambda x: (enc_raw(x), None),
                  lambda _, g: (dec_raw(g),))

    @jax.custom_vjp
    def decode(y):
        return dec_raw(y)

    decode.defvjp(lambda y: (dec_raw(y), None),
                  lambda _, g: (enc_raw(g),))
    return encode, decode


@functools.lru_cache(maxsize=None)
def make_a2a_codec(axis_name: str, fmt_name: str, dtype_name: str):
    """One custom-VJP wire trip ``decode(all_to_all(encode(x)))`` over
    leading-axis blocks, or ``None`` for the identity format (the caller
    keeps its untouched all_to_all line). ``all_to_all(split_axis=0,
    concat_axis=0)`` is its own transpose, so the bwd is the SAME trip
    on the cotangent — which is exactly "the cotangent rides the reverse
    wire encoded"."""
    enc, dec = make_wire_transform(fmt_name, dtype_name)
    if enc is None:
        return None

    def _trip(v):
        return dec(lax.all_to_all(enc(v), axis_name,
                                  split_axis=0, concat_axis=0))

    @jax.custom_vjp
    def wire_a2a(x):
        return _trip(x)

    wire_a2a.defvjp(lambda x: (_trip(x), None), lambda _, g: (_trip(g),))
    return wire_a2a


@functools.lru_cache(maxsize=None)
def make_ppermute_codec(axis_name: str, perm: tuple, fmt_name: str,
                        dtype_name: str):
    """One custom-VJP wire trip ``decode(ppermute(encode(x), perm))``,
    or ``None`` for the identity format. The bwd trip rides the INVERSE
    permutation (ppermute's transpose), cotangent encoded."""
    enc, dec = make_wire_transform(fmt_name, dtype_name)
    if enc is None:
        return None
    fwd_perm = tuple((int(s), int(d)) for s, d in perm)
    inv_perm = tuple((d, s) for s, d in fwd_perm)

    def _trip(v, p):
        return dec(lax.ppermute(enc(v), axis_name, p))

    @jax.custom_vjp
    def wire_pp(x):
        return _trip(x, fwd_perm)

    wire_pp.defvjp(lambda x: (_trip(x, fwd_perm), None),
                   lambda _, g: (_trip(g, inv_perm),))
    return wire_pp


def encode_compensated(x, resid, fmt_name: str):
    """Error-feedback encode (jax mirror of
    :func:`dgraph_tpu.wire.spec.np_encode_compensated`): quantize
    ``x + resid`` and return ``(wire_payload, new_resid)`` with the
    residual carried at f32. Thread ``new_resid`` into the next step;
    ``resid=None`` starts at zero. With the identity format the payload
    is ``x`` unchanged and the residual stays zero."""
    enc, dec = make_wire_transform(fmt_name, "float32")
    x32 = x.astype(jnp.float32)
    carried = x32 if resid is None else x32 + resid.astype(jnp.float32)
    if enc is None:
        return carried, jnp.zeros_like(carried)
    y = enc(carried)
    return y, carried - dec(y).astype(jnp.float32)
