"""Hub-row dedup: send each replicated boundary row once, fan out by relay.

Power-law graphs concentrate boundary incidence: one high-degree "hub"
vertex is needed by MANY ranks' halos, and every dense lowering (and the
direct compiled schedule) ships its feature row once PER NEEDER from the
owning rank — the owner's egress link pays degree times for one row.
This pass detects those rows at plan-build time, reduces the traffic
matrix so the owner sends each hub row to ONE primary needer, and
compiles extra relay rounds (recursive-doubling broadcast among the
needers) that fan the row out — the owner's egress cost drops from
``len(needers)`` rows to 1, and the relay hops spread across ranks that
were otherwise idle.

Scope: this is a *planning and verification* pass — it proves the
dedup'd round structure delivers every (needer, row) demand exactly once
(reusing :func:`dgraph_tpu.sched.ir.verify_schedule` for the direct
rounds plus a store-and-forward delivery simulation for the relays) and
prices the egress savings. The runtime ``sched`` executor still replays
direct schedules; wiring relay forwarding into the executor is future
work gated on this verifier (docs/wire-formats.md is explicit about the
boundary).

Contracts (same as :mod:`dgraph_tpu.sched.ir`): jax-free, deterministic,
every node a frozen dataclass of ints/tuples, so a dedup plan can be
hashed, serialized, and verified on a host with no accelerator.

Input convention: ``send_idx[src, dst, slot]`` is the owner-local row id
``src`` packs into slot ``slot`` of its (src -> dst) send block;
``send_mask[src, dst, slot]`` is 1 for live slots — exactly the plan's
halo send tables with the leading ``[world_size]`` axis kept.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from dgraph_tpu.sched.ir import HaloSchedule, verify_schedule
from dgraph_tpu.sched.passes import compile_halo_schedule


@dataclasses.dataclass(frozen=True)
class HubRow:
    """One boundary row replicated into ``len(needers)`` ranks' halos.
    ``primary`` (the lowest-ranked needer) receives it directly; the
    rest receive it by relay."""

    src: int
    row: int
    needers: tuple  # tuple[int, ...], sorted, len >= min_fanout
    @property
    def primary(self) -> int:
        return self.needers[0]


@dataclasses.dataclass(frozen=True)
class RelayTransfer:
    """One store-and-forward hop: ``carrier`` (which already holds the
    hub row ``(src, row)``) ships it to needer ``dst``."""

    carrier: int
    dst: int
    src: int
    row: int


@dataclasses.dataclass(frozen=True)
class DedupPlan:
    """The verified artifact: reduced direct schedule + relay rounds.

    ``reduced_live[s][d]`` is the tuple of owner-local row ids the
    reduced (s -> d) block still carries (hub rows only at their primary
    needer); ``reduced_pair_rows`` is its count matrix — the matrix the
    direct schedule is compiled and verified against.
    """

    world_size: int
    s_pad: int
    min_fanout: int
    hubs: tuple  # tuple[HubRow, ...]
    reduced_live: tuple  # [W][W] -> tuple[row ids]
    reduced_pair_rows: tuple  # [W][W] -> int
    direct_schedule: HaloSchedule
    relay_rounds: tuple  # tuple[tuple[RelayTransfer, ...], ...]


def pair_live_rows(send_idx, send_mask) -> tuple:
    """``[W][W]`` tuple-of-tuples of live owner-local row ids per
    (src, dst) send block, slot order preserved, duplicates dropped
    deterministically (first slot wins). Diagonal blocks are never live
    on the wire and are returned empty."""
    idx = np.asarray(send_idx)
    msk = np.asarray(send_mask)
    if idx.ndim != 3 or idx.shape != msk.shape:
        raise ValueError(
            f"send_idx/send_mask must be matching [W, W, S]; got "
            f"{idx.shape} vs {msk.shape}"
        )
    W = idx.shape[0]
    out = []
    for s in range(W):
        row = []
        for d in range(W):
            if s == d:
                row.append(())
                continue
            live = idx[s, d][msk[s, d].astype(bool)]
            row.append(tuple(dict.fromkeys(int(v) for v in live)))
        out.append(tuple(row))
    return tuple(out)


def detect_hub_rows(send_idx, send_mask, min_fanout: int = 2) -> tuple:
    """Rows replicated into at least ``min_fanout`` ranks' halos, as
    :class:`HubRow` records sorted by (src, row)."""
    live = pair_live_rows(send_idx, send_mask)
    W = len(live)
    hubs = []
    for s in range(W):
        needers: dict = {}
        for d in range(W):
            for r in live[s][d]:
                needers.setdefault(r, []).append(d)
        for r in sorted(needers):
            ds = sorted(needers[r])
            if len(ds) >= max(2, int(min_fanout)):
                hubs.append(HubRow(src=s, row=r, needers=tuple(ds)))
    return tuple(sorted(hubs, key=lambda h: (h.src, h.row)))


def _relay_stages(hub: HubRow) -> list:
    """Recursive-doubling broadcast among the needers: every rank that
    holds the row forwards it each stage, so ``k`` needers are covered
    in ``ceil(log2 k)`` relay stages instead of a depth-``k`` chain."""
    holders = [hub.primary]
    pending = list(hub.needers[1:])
    stages = []
    while pending:
        stage = []
        grown = []
        for h in holders:
            if not pending:
                break
            d = pending.pop(0)
            stage.append(RelayTransfer(carrier=h, dst=d,
                                       src=hub.src, row=hub.row))
            grown.append(d)
        holders.extend(grown)
        stages.append(stage)
    return stages


def _pack_relay_rounds(stages_by_depth: list) -> tuple:
    """Greedy conflict-free packing of each depth's relays (no rank
    twice as carrier or twice as receiver per round — the same
    one-ppermute budget :func:`verify_schedule` enforces). Depth order
    is preserved, so every carrier provably received its row in an
    earlier round."""
    rounds = []
    for stage in stages_by_depth:
        remaining = sorted(stage, key=lambda t: (t.src, t.row, t.dst))
        while remaining:
            used_c: set = set()
            used_d: set = set()
            packed = []
            rest = []
            for t in remaining:
                if t.carrier not in used_c and t.dst not in used_d:
                    used_c.add(t.carrier)
                    used_d.add(t.dst)
                    packed.append(t)
                else:
                    rest.append(t)
            rounds.append(tuple(packed))
            remaining = rest
    return tuple(rounds)


def build_dedup_plan(send_idx, send_mask, *, s_pad: int,
                     min_fanout: int = 2) -> DedupPlan:
    """Detect hubs, reduce the traffic matrix to primary-needer-only for
    hub rows, compile + verify the direct schedule against the REDUCED
    matrix, and pack the relay fan-out rounds."""
    live = pair_live_rows(send_idx, send_mask)
    W = len(live)
    hubs = detect_hub_rows(send_idx, send_mask, min_fanout)
    drop = {(h.src, d, h.row) for h in hubs for d in h.needers[1:]}
    reduced_live = tuple(
        tuple(
            tuple(r for r in live[s][d] if (s, d, r) not in drop)
            for d in range(W)
        )
        for s in range(W)
    )
    reduced_pair_rows = tuple(
        tuple(len(reduced_live[s][d]) for d in range(W)) for s in range(W)
    )
    direct = compile_halo_schedule(
        reduced_pair_rows, s_pad=int(s_pad), world_size=W
    )
    depth = max((len(_relay_stages(h)) for h in hubs), default=0)
    stages_by_depth = [[] for _ in range(depth)]
    for h in hubs:
        for i, stage in enumerate(_relay_stages(h)):
            stages_by_depth[i].extend(stage)
    return DedupPlan(
        world_size=W,
        s_pad=int(s_pad),
        min_fanout=max(2, int(min_fanout)),
        hubs=hubs,
        reduced_live=reduced_live,
        reduced_pair_rows=reduced_pair_rows,
        direct_schedule=direct,
        relay_rounds=_pack_relay_rounds(stages_by_depth),
    )


def verify_dedup_coverage(plan: DedupPlan, send_idx, send_mask) -> list:
    """Prove the dedup'd structure still delivers EXACTLY the original
    demand — the invariant that lets a lossy-looking rewrite claim bit
    parity. Failure list (empty == verified):

    - the direct schedule passes :func:`verify_schedule` against the
      reduced matrix (bounds / conflict-freedom / exact coverage);
    - relay rounds are conflict-free and causal: every carrier already
      holds the row (received it directly as primary, or by an earlier
      relay round) — a relay from a non-holder would forward garbage;
    - store-and-forward delivery simulation ends with every original
      (needer, src, row) demand delivered exactly ONCE: a gap is a
      dropped halo block, a double delivery is the double-count the
      reverse reduce would turn into a wrong gradient.

    The selftest's vacuity mutants (a duplicated relay, a dropped
    needer) must each turn this list non-empty.
    """
    failures = list(verify_schedule(plan.direct_schedule,
                                    plan.reduced_pair_rows))
    live = pair_live_rows(send_idx, send_mask)
    W = len(live)
    demand = {(d, s, r) for s in range(W) for d in range(W)
              for r in live[s][d]}
    delivered: dict = {}
    holders: dict = {}
    for s in range(W):
        for d in range(W):
            for r in plan.reduced_live[s][d]:
                delivered[(d, s, r)] = delivered.get((d, s, r), 0) + 1
                holders.setdefault((s, r), set()).add(d)
    for k, rnd in enumerate(plan.relay_rounds):
        carriers: set = set()
        receivers: set = set()
        for t in rnd:
            tag = f"relay round {k}: {t.carrier}->{t.dst} of ({t.src},{t.row})"
            if t.carrier in carriers:
                failures.append(f"{tag}: carrier sends twice in one round")
            if t.dst in receivers:
                failures.append(f"{tag}: rank receives twice in one round")
            carriers.add(t.carrier)
            receivers.add(t.dst)
            held = holders.get((t.src, t.row), set())
            if t.carrier not in held:
                failures.append(
                    f"{tag}: carrier does not hold the row yet "
                    f"(non-causal relay forwards garbage)"
                )
            delivered[(t.dst, t.src, t.row)] = (
                delivered.get((t.dst, t.src, t.row), 0) + 1
            )
        # holders grow only after the round completes (store-and-forward)
        for t in rnd:
            holders.setdefault((t.src, t.row), set()).add(t.dst)
    for key in sorted(demand):
        n = delivered.pop(key, 0)
        d, s, r = key
        if n == 0:
            failures.append(
                f"demand ({s},{r})->rank {d}: never delivered "
                f"(dropped needer — the halo block silently never arrives)"
            )
        elif n > 1:
            failures.append(
                f"demand ({s},{r})->rank {d}: delivered {n} times "
                f"(double-count — the reverse reduce would sum it twice)"
            )
    for key, n in sorted(delivered.items()):
        d, s, r = key
        failures.append(
            f"delivery ({s},{r})->rank {d} x{n} has no matching demand"
        )
    return failures


def dedup_stats(plan: DedupPlan, send_idx, send_mask) -> dict:
    """Egress accounting: what the owner links stop paying. Total hop
    count is conserved (store-and-forward moves the same rows), so the
    honest headline is BOTTLENECK egress, not total volume."""
    live = pair_live_rows(send_idx, send_mask)
    W = len(live)
    egress_before = [sum(len(live[s][d]) for d in range(W))
                     for s in range(W)]
    direct_after = [sum(plan.reduced_pair_rows[s][d] for d in range(W))
                    for s in range(W)]
    relay_out = [0] * W
    for rnd in plan.relay_rounds:
        for t in rnd:
            relay_out[t.carrier] += 1
    egress_after = [direct_after[s] + relay_out[s] for s in range(W)]
    return {
        "hubs_found": len(plan.hubs),
        "hub_needers_max": max((len(h.needers) for h in plan.hubs),
                               default=0),
        "owner_egress_rows_saved": sum(
            len(h.needers) - 1 for h in plan.hubs
        ),
        "relay_rows": sum(len(r) for r in plan.relay_rounds),
        "relay_rounds": len(plan.relay_rounds),
        "direct_rounds": plan.direct_schedule.num_rounds,
        "rows_total_before": sum(egress_before),
        "rows_direct_after": sum(direct_after),
        "max_rank_egress_before": max(egress_before, default=0),
        "max_rank_egress_after": max(egress_after, default=0),
    }
