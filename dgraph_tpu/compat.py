"""JAX version compatibility shims.

The codebase targets the jax >= 0.6 public API (``jax.shard_map``,
``jax.set_mesh``); some environments (including this one) pin jax 0.4.x,
where the same machinery lives at ``jax.experimental.shard_map.shard_map``
(with ``check_rep`` instead of ``check_vma``) and an ambient mesh is
entered via the ``Mesh`` context manager. Importing :mod:`dgraph_tpu`
installs forward-compatible aliases onto the ``jax`` module so every call
site — library, experiments, and tests — can use the one modern spelling.

On a modern jax this module is a no-op; the shims only fill attributes
that are absent, never replace existing ones.
"""

from __future__ import annotations

import jax


def _shard_map_04x(f=None, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` signature on top of 0.4.x experimental shard_map.

    Differences bridged: keyword-only ``mesh``; ``check_vma`` (0.6 name for
    the replication/varying-manual-axes check) forwards to ``check_rep``;
    bare-decorator form (``f=None``) returns a partial like 0.6 does.
    """
    from jax.experimental.shard_map import shard_map as _sm

    if check_vma is not None:
        kw["check_rep"] = check_vma
    if f is None:
        return lambda g: _sm(
            g, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def jax_version() -> tuple:
    try:
        return tuple(int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:  # dev/dirty version strings: assume modern
        return (99, 0)


# jax < 0.6: shard_map has no varying-manual-axes (vma) tracking, so an
# in-body ``jax.grad`` of replicated (in_specs P()) params yields PER-SHARD
# partial grads — the automatic psum the 0.6+ pvary-transpose inserts never
# happens, and out_specs P() either trips check_rep or silently returns one
# shard's partials. Training bodies must psum such grads explicitly there.
EXPLICIT_INBODY_GRAD_PSUM = jax_version() < (0, 6)


def sync_inbody_grads(grads, axis_names):
    """psum in-body grads of replicated params over the axes the loss is
    sharded on. Identity on jax >= 0.6 (vma tracking already inserted the
    psum; an explicit one would double-count by the axis size)."""
    if not EXPLICIT_INBODY_GRAD_PSUM:
        return grads
    from jax import lax

    return jax.tree.map(lambda g: lax.psum(g, axis_names), grads)


# shard_map kwargs that relax the replication checker where 0.4.x's
# rep tracking raises false positives (e.g. "branches of cond produced
# mismatched replication types" when AD re-traces ring attention's
# causal lax.cond). Empty on jax >= 0.6, whose vma system tracks these
# correctly — sprinkle ONLY at call sites with fully sharded out_specs,
# where the checker protects nothing.
RELAXED_CHECKS = {"check_vma": False} if jax_version() < (0, 6) else {}


def _pcast_04x(t, axis_name, *, to="varying"):
    """0.4.x has no vma system, so there is no device-varying type to cast
    to; the rep-tracking rewrite handles broadcasts itself. Identity."""
    del axis_name, to
    return t


def _set_mesh_04x(mesh):
    """``jax.set_mesh`` context form on 0.4.x: the ``Mesh`` object is its
    own context manager, and every shard_map here passes ``mesh=``
    explicitly, so entering the physical mesh context is all the ambient
    state the library needs."""
    return mesh


def install() -> None:
    """Idempotently fill missing jax attributes (called on package import)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_04x
    if not hasattr(jax, "set_mesh"):
        jax.set_mesh = _set_mesh_04x
    from jax import lax

    if not hasattr(lax, "pcast"):
        lax.pcast = _pcast_04x


install()
