"""JAX version compatibility shims.

The codebase targets the jax >= 0.6 public API (``jax.shard_map``,
``jax.set_mesh``); some environments (including this one) pin jax 0.4.x,
where the same machinery lives at ``jax.experimental.shard_map.shard_map``
(with ``check_rep`` instead of ``check_vma``) and an ambient mesh is
entered via the ``Mesh`` context manager. Importing :mod:`dgraph_tpu`
installs forward-compatible aliases onto the ``jax`` module so every call
site — library, experiments, and tests — can use the one modern spelling.

On a modern jax this module is a no-op; the shims only fill attributes
that are absent, never replace existing ones.
"""

from __future__ import annotations

import jax


def _shard_map_04x(f=None, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """``jax.shard_map`` signature on top of 0.4.x experimental shard_map.

    Differences bridged: keyword-only ``mesh``; ``check_vma`` (0.6 name for
    the replication/varying-manual-axes check) forwards to ``check_rep``;
    bare-decorator form (``f=None``) returns a partial like 0.6 does.
    """
    from jax.experimental.shard_map import shard_map as _sm

    if check_vma is not None:
        kw["check_rep"] = check_vma
    if f is None:
        return lambda g: _sm(
            g, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def jax_version() -> tuple:
    try:
        return tuple(int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:  # dev/dirty version strings: assume modern
        return (99, 0)


# jax < 0.6: shard_map has no varying-manual-axes (vma) tracking, so an
# in-body ``jax.grad`` of replicated (in_specs P()) params yields PER-SHARD
# partial grads — the automatic psum the 0.6+ pvary-transpose inserts never
# happens, and out_specs P() either trips check_rep or silently returns one
# shard's partials. Training bodies must psum such grads explicitly there.
EXPLICIT_INBODY_GRAD_PSUM = jax_version() < (0, 6)


def sync_inbody_grads(grads, axis_names):
    """psum in-body grads of replicated params over the axes the loss is
    sharded on. Identity on jax >= 0.6 (vma tracking already inserted the
    psum; an explicit one would double-count by the axis size)."""
    if not EXPLICIT_INBODY_GRAD_PSUM:
        return grads
    from jax import lax

    return jax.tree.map(lambda g: lax.psum(g, axis_names), grads)


# shard_map kwargs that relax the replication checker where 0.4.x's
# rep tracking raises false positives (e.g. "branches of cond produced
# mismatched replication types" when AD re-traces ring attention's
# causal lax.cond). Empty on jax >= 0.6, whose vma system tracks these
# correctly — sprinkle ONLY at call sites with fully sharded out_specs,
# where the checker protects nothing.
RELAXED_CHECKS = {"check_vma": False} if jax_version() < (0, 6) else {}


def _pcast_04x(t, axis_name, *, to="varying"):
    """0.4.x has no vma system, so there is no device-varying type to cast
    to; the rep-tracking rewrite handles broadcasts itself. Identity."""
    del axis_name, to
    return t


def _set_mesh_04x(mesh):
    """``jax.set_mesh`` context form on 0.4.x: the ``Mesh`` object is its
    own context manager, and every shard_map here passes ``mesh=``
    explicitly, so entering the physical mesh context is all the ambient
    state the library needs."""
    return mesh


_dma_patch_installed = False


def install_multiaxis_remote_dma() -> None:
    """Teach Pallas interpret mode's remote-DMA discharge about multi-axis
    meshes (idempotent; called lazily by :mod:`dgraph_tpu.ops.pallas_p2p`).

    jax 0.4.x's ``dma_start`` discharge rule — what runs a
    ``make_async_remote_copy`` put under ``pallas_call(interpret=True)`` —
    raises NotImplementedError whenever the axis env holds more than one
    named axis, and every dgraph mesh is ``('replica', 'graph')``. The
    underlying machinery generalizes directly: a LOGICAL device id is the
    raveled index over the mesh axes (row-major in axis-env order), so
    the patched rule all-gathers over the TUPLE of named axes and matches
    the sender by that raveled id. Single-axis envs defer verbatim to the
    original rule — zero behavior change anywhere else.
    (:func:`dgraph_tpu.ops.pallas_p2p.p2p_transport` computes its device
    ids with the same raveling, so interpret mode and real Mosaic
    lowerings agree.)"""
    global _dma_patch_installed
    if _dma_patch_installed:
        return
    if jax_version() >= (0, 6):
        # the patch is built from 0.4.x internals; on newer jax defer to
        # upstream entirely — if its interpret mode still cannot discharge
        # a multi-axis remote DMA, its own NotImplementedError surfaces
        # loudly, which beats silently replacing a working rule with
        # 0.4.x-semantics code (the RELAXED_CHECKS gating precedent)
        _dma_patch_installed = True
        return
    import jax.numpy as jnp
    from jax import tree_util
    from jax._src import core as jax_core
    from jax._src.pallas import core as pl_core
    from jax._src.pallas.mosaic import primitives as _prims
    from jax._src.state import discharge as state_discharge

    original = _prims.dma_start_discharge_rule

    def patched(in_avals, out_avals, *args, tree, device_id_type):
        axis_env = jax_core.get_axis_env()
        nonempty = [n for n in axis_env.axis_sizes if n is not None]
        if (
            len(nonempty) <= 1
            or device_id_type != _prims.DeviceIdType.LOGICAL
        ):
            return original(
                in_avals, out_avals, *args, tree=tree,
                device_id_type=device_id_type,
            )
        (src_ref, src_transforms, dst_ref, dst_transforms, dst_sem,
         dst_sem_transforms, src_sem, src_sem_transforms, device_id,
         ) = tree_util.tree_unflatten(tree, args)
        (_, src_transforms_avals, _, dst_transforms_avals, dst_sem_aval,
         dst_sem_transforms_avals, src_sem_aval, src_sem_transforms_avals,
         _) = tree_util.tree_unflatten(tree, in_avals)
        del out_avals
        num_src_sem_t = len(tree_util.tree_leaves(src_sem_transforms_avals))
        num_dst_sem_t = len(tree_util.tree_leaves(dst_sem_transforms_avals))
        num_src_t = len(tree_util.tree_leaves(src_transforms_avals))
        num_dst_t = len(tree_util.tree_leaves(dst_transforms_avals))

        updates = state_discharge.transform_array(src_ref, src_transforms)
        local_src = updates

        # raveled logical id over ALL named axes, row-major in env order
        axes = tuple(nonempty)
        sizes = [axis_env.axis_sizes[a] for a in axes]
        my_logical = 0
        for a, s in zip(axes, sizes):
            my_logical = my_logical * s + jax.lax.axis_index(a)
        who_copy_to_me = jax.lax.all_gather(device_id, axes) == my_logical
        index = jnp.argmax(who_copy_to_me, axis=0)
        global_updates = jax.lax.all_gather(updates, axes)
        updates = jax.lax.dynamic_index_in_dim(
            global_updates, index, axis=0, keepdims=False)
        global_dst_t = tree_util.tree_map(
            lambda x: jax.lax.all_gather(x, axes), dst_transforms)
        dst_transforms = tree_util.tree_map(
            lambda x: jax.lax.dynamic_index_in_dim(
                x, index, axis=0, keepdims=False),
            global_dst_t,
        )
        _, new_dst = state_discharge.transform_swap_array(
            dst_ref, dst_transforms, updates)

        recv_size = jnp.minimum(updates.size, pl_core.SEMAPHORE_MAX_VALUE)
        recv_size = jnp.array(
            recv_size, dtype=pl_core.SEMAPHORE_INTERPRET_DTYPE)
        dst_sem_value = _prims._transform_semaphore(
            dst_sem, dst_sem_transforms, dst_sem_aval)
        _, new_dst_sem = state_discharge.transform_swap_array(
            dst_sem, dst_sem_transforms, dst_sem_value + recv_size)
        send_size = jnp.minimum(local_src.size, pl_core.SEMAPHORE_MAX_VALUE)
        send_size = jnp.array(
            send_size, dtype=pl_core.SEMAPHORE_INTERPRET_DTYPE)
        src_sem_value = _prims._transform_semaphore(
            src_sem, src_sem_transforms, src_sem_aval)
        _, new_src_sem = state_discharge.transform_swap_array(
            src_sem, src_sem_transforms, src_sem_value + send_size)

        new_vals = (None,) + (None,) * num_src_t
        new_vals += (new_dst,) + (None,) * num_dst_t
        new_vals += (new_dst_sem,) + (None,) * num_dst_sem_t
        new_vals += (new_src_sem,) + (None,) * num_src_sem_t
        new_vals += (None,)  # device_id
        assert len(new_vals) == len(in_avals)
        return new_vals, []

    state_discharge.register_discharge_rule(_prims.dma_start_p)(patched)
    _dma_patch_installed = True


def install() -> None:
    """Idempotently fill missing jax attributes (called on package import)."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_04x
    if not hasattr(jax, "set_mesh"):
        jax.set_mesh = _set_mesh_04x
    from jax import lax

    if not hasattr(lax, "pcast"):
        lax.pcast = _pcast_04x


install()
