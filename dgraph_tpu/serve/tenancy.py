"""Per-tenant isolation: token-bucket rate quotas, bounded queue shares,
and per-tenant degraded shedding.

The single-tenant serving stack (PR 2/5) already bounds *total* overload —
queue depth, deadlines, engine-level degraded mode — but one misbehaving
tenant spends those shared bounds for everyone: a flood fills the queue and
every other tenant sees ``backpressure``; a stream of poisoned payloads
burns the retry budget and degrades the whole engine. This module makes
each of those bounds *per tenant*, so the blast radius of one tenant's
misbehavior is that tenant alone:

- **Rate quota** — a :class:`TokenBucket` per tenant (``rps`` refill,
  ``burst`` capacity). An empty bucket rejects at submit with the
  structured :class:`~dgraph_tpu.serve.errors.QuotaExceeded` — the flood
  never occupies a queue slot.
- **Queue share** — each tenant may hold at most ``max_queue_share`` of
  the batcher's bounded queue. A tenant at its share is rejected with
  ``quota`` even when the queue has room, so a burst that fits the rate
  quota still cannot starve other tenants of queue space.
- **Per-tenant degraded** — ``degrade_after`` consecutive *failed* served
  requests (the engine raised, not a quota rejection) flip just that
  tenant into degraded shedding (:class:`~dgraph_tpu.serve.errors.
  TenantDegraded`) until the operator calls :meth:`TenantTable.reset` —
  PR 5's engine-level degraded mode, scoped to the tenant whose payloads
  are failing.

This module is **jax-free by contract** (``analysis.lint``'s
``jax-free-module`` rule): quota bookkeeping is control-plane state the
supervisor may inspect in processes that never dial a backend. Clocks are
injectable (``clock=``) so every policy is testable deterministically.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from dgraph_tpu.serve.errors import QuotaExceeded, TenantDegraded

# the tenant id requests without an explicit tenant are accounted under;
# quota enforcement applies to it like any other tenant
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Admission policy for one tenant (or the table-wide default).

    ``rps`` / ``burst`` parameterize the token bucket (``rps <= 0`` means
    unlimited rate); ``max_queue_share`` bounds the fraction of the
    batcher's queue one tenant may occupy; ``degrade_after`` consecutive
    served-request failures flip the tenant into degraded shedding
    (``0`` disables per-tenant degrading).
    """

    rps: float = 0.0
    burst: int = 8
    max_queue_share: float = 0.5
    degrade_after: int = 0

    def __post_init__(self):
        if self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        if not 0.0 < self.max_queue_share <= 1.0:
            raise ValueError(
                f"max_queue_share must be in (0, 1], got {self.max_queue_share}"
            )
        if self.degrade_after < 0:
            raise ValueError(
                f"degrade_after must be >= 0, got {self.degrade_after}"
            )


class TokenBucket:
    """Classic token bucket on an injectable monotonic clock.

    ``take()`` consumes one token when available; refill is continuous at
    ``rps`` up to ``burst`` capacity. Not thread-safe on its own — the
    owning :class:`TenantTable` serializes access under its lock.
    """

    def __init__(self, rps: float, burst: int,
                 clock: Callable[[], float] = time.monotonic):
        self.rps = float(rps)
        self.burst = int(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def take(self) -> bool:
        if self.rps <= 0:
            return True  # unlimited rate; queue share still bounds space
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rps
        )
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class _TenantState:
    __slots__ = (
        "bucket", "quota", "queued", "admitted", "shed_quota",
        "shed_degraded", "failures", "consecutive_failures", "degraded",
    )

    def __init__(self, quota: TenantQuota, clock):
        self.quota = quota
        self.bucket = TokenBucket(quota.rps, quota.burst, clock)
        self.queued = 0  # requests currently occupying queue slots
        self.admitted = 0
        self.shed_quota = 0
        self.shed_degraded = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.degraded = False


class TenantTable:
    """Thread-safe per-tenant admission + failure accounting.

    The :class:`~dgraph_tpu.serve.batcher.MicroBatcher` consults
    :meth:`admit` at submit (client threads) and reports outcomes from its
    worker thread via :meth:`release` / :meth:`observe_failure` /
    :meth:`observe_success`; :meth:`snapshot` feeds the per-tenant section
    of ``serve_health_record``. Unknown tenants are admitted under
    ``default_quota`` and materialize state lazily.
    """

    def __init__(
        self,
        default_quota: Optional[TenantQuota] = None,
        quotas: Optional[dict] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        max_tenants: int = 1024,
    ):
        if max_tenants < 1:
            raise ValueError(f"max_tenants must be >= 1, got {max_tenants}")
        self.default_quota = default_quota or TenantQuota()
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict = {}
        for name, q in (quotas or {}).items():
            self._tenants[str(name)] = _TenantState(q, clock)

    def _state(self, tenant: str) -> tuple:
        """(resolved tenant id, state). Tenant ids are client-supplied, so
        lazily-materialized state is CAPPED at ``max_tenants``: past the
        cap, unseen ids fold into the shared :data:`DEFAULT_TENANT` bucket
        (admission keeps working, bounded-memory, degraded-gracefully)
        instead of letting an id-per-request client grow process memory
        without bound."""
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= self.max_tenants:
                tenant = DEFAULT_TENANT
                st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantState(
                    self.default_quota, self._clock
                )
        return tenant, st

    def admit(self, tenant: Optional[str], max_queue_depth: int) -> str:
        """Admission check for one request; returns the resolved tenant id
        or raises the structured rejection. On success the tenant's queue
        occupancy is incremented — the caller MUST pair every successful
        admit with exactly one :meth:`release` (whatever way the request
        resolves)."""
        t = DEFAULT_TENANT if tenant is None else str(tenant)
        with self._lock:
            t, st = self._state(t)
            if st.degraded:
                st.shed_degraded += 1
                raise TenantDegraded(
                    f"tenant {t!r} is degraded after "
                    f"{st.consecutive_failures} consecutive request "
                    "failures; shedding until reset",
                    tenant=t,
                    consecutive_failures=st.consecutive_failures,
                )
            share_cap = max(
                1, int(st.quota.max_queue_share * max_queue_depth)
            )
            if st.queued >= share_cap:
                st.shed_quota += 1
                raise QuotaExceeded(
                    f"tenant {t!r} holds {st.queued} of its {share_cap} "
                    "queue slots; retry with backoff",
                    tenant=t, reason="queue_share",
                    queued=st.queued, share_cap=share_cap,
                )
            if not st.bucket.take():
                st.shed_quota += 1
                raise QuotaExceeded(
                    f"tenant {t!r} exceeded its rate quota "
                    f"({st.quota.rps} rps, burst {st.quota.burst})",
                    tenant=t, reason="rate",
                    rps=st.quota.rps, burst=st.quota.burst,
                )
            st.queued += 1
            st.admitted += 1
            return t

    def release(self, tenant: str) -> None:
        """The request admitted for ``tenant`` left the queue (served,
        rejected, expired, cancelled, crashed — every resolution path)."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None and st.queued > 0:
                st.queued -= 1

    def observe_success(self, tenant: str) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None:
                st.consecutive_failures = 0

    def observe_failure(self, tenant: str) -> bool:
        """One served request from ``tenant`` failed in the engine; returns
        True when this failure flipped the tenant into degraded mode."""
        with self._lock:
            _, st = self._state(str(tenant))
            st.failures += 1
            st.consecutive_failures += 1
            if (
                st.quota.degrade_after
                and not st.degraded
                and st.consecutive_failures >= st.quota.degrade_after
            ):
                st.degraded = True
                return True
            return False

    def reset(self, tenant: str) -> None:
        """Operator re-admission of a degraded tenant (mirrors
        ``ServeEngine.reset_degraded`` — explicit on purpose; auto-undegrading
        would flap against a client that is still sending poison)."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is not None:
                st.degraded = False
                st.consecutive_failures = 0

    def snapshot(self) -> dict:
        """Per-tenant counters for the serve_health record."""
        with self._lock:
            return {
                t: {
                    "admitted": st.admitted,
                    "queued": st.queued,
                    "shed_quota": st.shed_quota,
                    "shed_degraded": st.shed_degraded,
                    "failures": st.failures,
                    "degraded": st.degraded,
                }
                for t, st in sorted(self._tenants.items())
            }
