"""Shape-bucketed request padding.

On TPU the online-latency killer is not FLOPs but XLA recompilation: a
jitted forward specializes on every distinct input shape, and a fresh
compile is O(seconds) against a per-request budget of milliseconds. The
same padded-static-shape discipline the :class:`~dgraph_tpu.plan.EdgePlan`
applies to graph structure (pad every per-peer segment to one static
maximum) is applied here to *request* shape: target-node counts are rounded
up a small geometric ladder of bucket sizes, every bucket is compiled once
ahead of time (``ServeEngine.warmup``), and the hot path only ever replays
cached executables. Padding waste is bounded by the ladder's growth factor
(< 2x rows at growth 2.0, and the padded rows are gather indices — bytes,
not model FLOPs); the obs registry's ``serve.batch_occupancy`` histogram is
the live measure of what the ladder actually costs.
"""

from __future__ import annotations

import bisect
import dataclasses
import math

import numpy as np

from dgraph_tpu.serve.errors import RequestTooLarge


@dataclasses.dataclass(frozen=True)
class BucketLadder:
    """Ascending tuple of target-node-count bucket sizes.

    One jitted forward (and one AOT warmup compile) exists per size, so the
    ladder should stay small — a geometric ladder covers a 128x dynamic
    range in 8 buckets at growth 2.0.
    """

    sizes: tuple

    def __post_init__(self):
        if not self.sizes:
            raise ValueError("BucketLadder needs at least one size")
        if any(s <= 0 for s in self.sizes):
            raise ValueError(f"bucket sizes must be positive: {self.sizes}")
        if any(b <= a for a, b in zip(self.sizes, self.sizes[1:])):
            raise ValueError(f"bucket sizes must be strictly ascending: {self.sizes}")

    @classmethod
    def geometric(
        cls, min_size: int = 8, max_size: int = 1024, growth: float = 2.0
    ) -> "BucketLadder":
        """min_size, ~min_size*growth, ... capped at exactly max_size."""
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1.0, got {growth}")
        if max_size < min_size:
            raise ValueError(f"max_size {max_size} < min_size {min_size}")
        sizes, s = [], min_size
        while s < max_size:
            sizes.append(s)
            s = max(s + 1, int(math.ceil(s * growth)))
        sizes.append(max_size)
        return cls(tuple(sizes))

    @property
    def max_size(self) -> int:
        return self.sizes[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` target nodes (``n=0`` maps to the
        smallest bucket — an all-padding gather is cheaper than a bucket
        shape that only ever appears in tests). Raises
        :class:`RequestTooLarge` past the ladder's top."""
        if n < 0:
            raise ValueError(f"negative request size {n}")
        if n > self.sizes[-1]:
            raise RequestTooLarge(
                f"request of {n} target nodes exceeds the largest bucket "
                f"({self.sizes[-1]}); split the request or raise max_bucket",
                request_size=int(n),
                max_bucket=int(self.sizes[-1]),
            )
        return self.sizes[bisect.bisect_left(self.sizes, n)]


def pad_ids(ids: np.ndarray, bucket: int) -> tuple:
    """Pad a [n] id vector to [bucket] with id 0 (any *valid* id — padded
    rows gather real logits that are sliced off, never out-of-bounds
    indices). Returns (padded int32 [bucket], n)."""
    ids = np.asarray(ids)
    if ids.ndim != 1:
        raise ValueError(f"node_ids must be 1-D, got shape {ids.shape}")
    n = ids.shape[0]
    if n > bucket:
        raise ValueError(f"{n} ids do not fit bucket {bucket}")
    out = np.zeros(bucket, np.int32)
    out[:n] = ids
    return out, n
