"""Serve-health record: one JSONL line that makes a serving process
diagnosable from its artifact alone.

The :class:`~dgraph_tpu.obs.health.RunHealth` discipline (host/env/backend
snapshot + structured outcome) extended with what an operator asks of a
*serving* process: the bucket ladder, the warmup cost, the recompile
counter (the steady-state SLO invariant — must be 0), latency percentiles
(p50/p95/p99 from the obs registry's histograms), and queue/backpressure
state. Emitted by ``python -m dgraph_tpu.serve`` on exit and by
``experiments/serve_bench.py`` alongside its throughput report.
"""

from __future__ import annotations

from typing import Optional

from dgraph_tpu.obs.health import RunHealth
from dgraph_tpu.obs.ledger import SERVE_HEALTH_SCHEMA_VERSION, maybe_ingest
from dgraph_tpu.obs.metrics import Metrics

# the registry histograms surfaced as headline latency numbers, in
# preference order (end-to-end queue+infer when a batcher ran, bare infer
# otherwise)
_LATENCY_HISTOGRAMS = ("serve.request_ms", "serve.infer_ms")

# per-stage request-lifecycle histograms (obs.spans instrumentation in the
# batcher/engine), folded into the record as p50/p95/p99 snapshots so
# "where did the latency go" is answerable from the artifact alone
_STAGES = ("queue_wait", "batch_form", "pad", "infer", "reply")


def _tenant_section(batcher, snap: dict) -> Optional[dict]:
    """Per-tenant isolation state: admission/shed/degraded counters from
    the TenantTable plus each tenant's p50/p95/p99 end-to-end latency (the
    noisy-neighbor artifact — tenant B's p99 staying bounded while tenant
    A floods is the isolation SLO, tracked from this record alone)."""
    table = getattr(batcher, "tenants", None)
    if table is None:
        return None
    out = table.snapshot()
    for tenant, rec in out.items():
        hist = snap["histograms"].get(f"serve.tenant.{tenant}.request_ms")
        if hist and hist.get("count"):
            rec["latency_ms"] = hist
    return out


def _wire_provenance(plan) -> Optional[dict]:
    """The wire format the engine's halo payloads ship with, plus who
    resolved it (env > record > plan > fp32 default) — pure static-aux
    reads, so a health snapshot never touches a device buffer."""
    if plan is None:
        return None
    try:
        from dgraph_tpu.wire.spec import resolve_wire_format

        name, source = resolve_wire_format(
            int(plan.world_size), tuple(plan.halo_deltas),
            plan_format=getattr(plan, "wire_format", "fp32"),
        )
        return {"format": name, "source": source}
    except Exception:  # provenance must never break a health snapshot
        return None


def serve_health_record(
    engine, batcher=None, *, registry: Optional[Metrics] = None
) -> dict:
    """One ``kind="serve_health"`` JSONL record for the serving process."""
    reg = registry if registry is not None else engine.registry
    h = RunHealth.begin("serve.engine")
    h.snapshot_backend()
    snap = reg.snapshot()
    latency = {"count": 0}
    for name in _LATENCY_HISTOGRAMS:
        hist = snap["histograms"].get(name)
        if hist and hist.get("count"):
            latency = {"source": name, **hist}
            break
    stages = {}
    for stage in _STAGES:
        hist = snap["histograms"].get(f"serve.stage.{stage}_ms")
        if hist and hist.get("count"):
            stages[stage] = hist
    rec = {
        "kind": "serve_health",
        # versioned against the ledger normalizer (one shared constant):
        # readers skip-with-reason on records newer than they understand
        "schema_version": SERVE_HEALTH_SCHEMA_VERSION,
        **h.finish(),
        "buckets": [int(b) for b in engine.ladder.sizes],
        "num_nodes": engine.num_nodes,
        "warmup_s": engine.warmup_s,
        "recompiles_since_warmup": engine.recompiles_since_warmup(),
        # self-healing state: True means the engine is shedding every
        # request as QueueFull after repeated device failures (the operator
        # re-admits with reset_degraded())
        "degraded": bool(getattr(engine, "degraded", False)),
        # the adopted tuning record (dgraph_tpu.tune) these latency numbers
        # were produced under, or None for the hard-coded defaults
        "tuning_record": getattr(engine, "tuning_record_id", None),
        # the wire codec the halo payloads ship with and who resolved it
        # (dgraph_tpu.wire) — same attribution discipline as the record
        "wire_format": _wire_provenance(getattr(engine, "_plan", None)),
        # control-plane provenance: checkpoint-rollover lineage (every
        # swap_params attempt, adopted or rolled back) and the adopted
        # graph-delta generation (dgraph_tpu.serve.deltas), so a latency
        # artifact is attributable to the exact (checkpoint, graph
        # generation) pair that served it
        "lineage": list(getattr(engine, "lineage", []) or []),
        "generation": getattr(engine, "generation", None),
        "latency_ms": latency,
        # per-stage breakdown (count/mean/p50/p95/p99 each): queue-wait vs
        # batch-form vs bucket-pad vs infer vs reply
        "stages_ms": stages,
        "metrics": snap,
    }
    if batcher is not None:
        rec["queue"] = {
            "depth": len(batcher),
            "max_depth": batcher.max_queue_depth,
            "max_batch_size": batcher.max_batch_size,
            "max_delay_ms": batcher.max_delay_ms,
        }
        tenants = _tenant_section(batcher, snap)
        if tenants is not None:
            rec["tenants"] = tenants
        # a registry-backed batcher: which named model is active and the
        # full version table (dgraph_tpu.serve.registry)
        source = getattr(batcher, "_source", None)
        if source is not None and hasattr(source, "active_engine"):
            rec["models"] = source.record()
    # longitudinal trajectory: serving latency joins the perf ledger when
    # DGRAPH_LEDGER_DIR is set (off by default — a serving process must
    # not write to a bench cache it doesn't own)
    maybe_ingest(rec, source="serve.health", default_on=False)
    return rec
