"""Online inference engine: checkpoint -> plan -> per-bucket jitted forward.

Serving a partitioned full-graph GNN differs from one more eval step in one
way that matters on TPU: requests arrive with arbitrary target-node counts,
and every novel shape reaching a jitted function is a multi-second XLA
compile in the middle of a millisecond latency budget. :class:`ServeEngine`
therefore holds ONE jitted, donated forward per :class:`~dgraph_tpu.serve.
bucketing.BucketLadder` size — each is the *same* shard_map forward the
train/eval steps run (``train.loop.model_apply``, so serve semantics cannot
drift from training) followed by a [bucket]-shaped gather of the requested
rows — and compiles all of them at startup (:meth:`warmup`). Steady state
replays cached executables only; :meth:`recompiles_since_warmup` is the
counter that proves it (pinned to 0 by ``--selftest`` and
``tests/test_serve.py``).

The request id space is the caller's ORIGINAL vertex numbering: the engine
carries the :class:`~dgraph_tpu.partition.Renumbering`-derived
``(rank, slot)`` map, so clients never see partition internals (the inverse
of what ``plan.unshard_vertex_data`` does for whole tensors, per-row).

The per-bucket forward is a registered audit program: the static-analysis
CLI traces it (:mod:`dgraph_tpu.analysis.trace`) AND lowers it
(:mod:`dgraph_tpu.analysis.hlo`, ISSUE 12) under every halo lowering —
collective schedule, operand bytes, and the donated ``(rank_idx,
slot_idx)`` scratch surviving lowering are all pinned against
``obs.footprint`` with zero compiles, so a serve-path schedule regression
is caught before any engine is ever warmed.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dgraph_tpu import compat as _compat  # noqa: F401  (jax.shard_map on 0.4.x)
from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
from dgraph_tpu.obs import spans
from dgraph_tpu.obs.metrics import Metrics, default_registry
from dgraph_tpu.serve.bucketing import BucketLadder, pad_ids
from dgraph_tpu.train.loop import model_apply


class ServeEngine:
    """Forward-only serving over one partitioned graph.

    Construction wires the static state (sharded params/features/plan and
    the original-id -> (rank, slot) map); :meth:`warmup` ahead-of-time
    compiles every bucket; :meth:`infer` is the hot path. Device arrays and
    jit caches live for the engine's lifetime — one engine per (graph,
    params) pair, shared by the micro-batcher's worker thread.
    """

    def __init__(
        self,
        model,
        mesh,
        plan,
        params,
        batch: dict,
        id_rank: np.ndarray,
        id_slot: np.ndarray,
        *,
        ladder: Optional[BucketLadder] = None,
        batch_args: Optional[Callable] = None,
        registry: Optional[Metrics] = None,
        tuning_record_id: Optional[str] = None,
        max_retries: int = 2,
        degrade_after: int = 3,
        retry_backoff_s: float = 0.05,
    ):
        self.model = model
        self.mesh = mesh
        self.ladder = ladder or BucketLadder.geometric()
        # self-healing knobs: a transient device error (lease blip, chaos
        # injection) is retried up to max_retries times per request; after
        # degrade_after CONSECUTIVE requests exhaust their retries the
        # engine degrades — sheds every request as QueueFull until
        # reset_degraded() — so a dead backend fails clients fast instead
        # of burning a retry storm per request
        self.max_retries = int(max_retries)
        self.degrade_after = int(degrade_after)
        self.retry_backoff_s = float(retry_backoff_s)
        self.degraded = False
        self._consecutive_failures = 0
        # the ENGINE lock: serializes the degraded-mode accounting (worker
        # thread) against reset_degraded / swap_params / append_vertices
        # (operator threads). The hot path never holds it across a device
        # dispatch — mutable state is flipped by single reference
        # assignments under the lock and read once per dispatch.
        self._lock = threading.RLock()
        # bumped by reset_degraded: a request DISPATCHED before a reset
        # must not count toward the fresh degrade window when it fails
        # after the reset (the resurrect-after-reset race this epoch
        # closes; pinned by tests/test_serve_control.py)
        self._failure_epoch = 0
        # provenance only (the ladder/plan themselves arrive already
        # built): stamped into serve_health so latency artifacts are
        # attributable to the tuning config that produced them
        self.tuning_record_id = tuning_record_id
        self.batch_args = batch_args
        self.registry = registry if registry is not None else default_registry
        self._plan = jax.tree.map(jnp.asarray, plan)
        self._batch = jax.tree.map(jnp.asarray, batch)
        # device-resident once: a checkpoint restore hands back numpy
        # leaves, and feeding those to jit re-transfers params every call
        self._params = jax.tree.map(jnp.asarray, params)
        self._id_rank = np.asarray(id_rank, np.int32)
        self._id_slot = np.asarray(id_slot, np.int32)
        if self._id_rank.shape != self._id_slot.shape:
            raise ValueError("id_rank / id_slot length mismatch")
        self.num_nodes = int(self._id_rank.shape[0])
        # host mirrors of the vertex-sharded batch leaves, for live delta
        # appends into reserved pad slots (append_vertices): mutate the
        # mirror, then flip self._batch to fresh device arrays in ONE
        # reference assignment
        self._host_x = np.asarray(batch["x"]) if "x" in batch else None
        self._host_vmask = (
            np.asarray(batch["vmask"]) if "vmask" in batch else None
        )
        # per-rank slot occupancy (real vertices per rank) — the free pad
        # slots above it are the append budget until the next re-plan
        world = next(iter(jax.tree.leaves(self._batch))).shape[0]
        self._slot_fill = np.bincount(
            self._id_rank, minlength=world
        ).astype(np.int64)
        # control-plane provenance: checkpoint lineage (swap_params
        # appends one record per rollover attempt) and the adopted graph
        # generation (dgraph_tpu.serve.deltas stamps it)
        self.ckpt_dir: Optional[str] = None
        self.lineage: list = []
        self.generation: Optional[int] = None
        self._batch_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
        self._plan_specs = plan_in_specs(self._plan)
        # one independently-jitted forward per bucket: per-bucket executables
        # AND per-bucket compile accounting (each fn's jit cache should hold
        # its one entry after warmup and never grow)
        self._forwards = {b: self._build_forward() for b in self.ladder.sizes}
        self._full = jax.jit(self._make_forward_body())
        self._compiles_at_warmup: Optional[int] = None
        self.warmup_s: Optional[float] = None

    # --- construction helpers ---

    @classmethod
    def from_distributed_graph(
        cls, model, mesh, g, params, **kwargs
    ) -> "ServeEngine":
        """Wire an engine from a :class:`~dgraph_tpu.data.graph.
        DistributedGraph`: forward-only batch (features + optional edge
        weights / vertex mask) and the original-id -> (rank, slot) map from
        its renumbering."""
        ren = g.ren
        rank = np.asarray(ren.partition)[np.asarray(ren.perm)]
        slot = np.asarray(ren.perm) - np.asarray(ren.offsets)[rank]
        batch = {"x": g.features, "vmask": g.vertex_mask}
        if g.edge_weight is not None:
            batch["edge_weight"] = g.edge_weight
        kwargs.setdefault(
            "tuning_record_id", getattr(g, "tuning_record_id", None)
        )
        return cls(model, mesh, g.plan, params, batch, rank, slot, **kwargs)

    @classmethod
    def from_checkpoint(
        cls,
        model,
        mesh,
        g,
        ckpt_dir: str,
        *,
        step: Optional[int] = None,
        template: Optional[dict] = None,
        **kwargs,
    ) -> "ServeEngine":
        """Restore params via :func:`~dgraph_tpu.train.checkpoint.
        restore_checkpoint` (newest readable step; corrupt steps fall back
        older) and build the engine. The checkpoint may be a bare params
        tree or a train-state dict with a ``'params'`` entry."""
        from dgraph_tpu.train.checkpoint import restore_checkpoint

        state = restore_checkpoint(ckpt_dir, template, step=step)
        if state is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir!r}")
        params = state["params"] if isinstance(state, dict) and "params" in state else state
        eng = cls.from_distributed_graph(model, mesh, g, params, **kwargs)
        # remember the lineage root: swap_params(step=...) resolves bare
        # step numbers against this directory
        eng.ckpt_dir = ckpt_dir
        eng.lineage.append({
            "kind": "serve_rollover",
            "event": "restore",
            "ckpt_dir": ckpt_dir,
            "step": int(step) if step is not None else (
                int(state["step"])
                if isinstance(state, dict) and "step" in state else None
            ),
            "adopted": True,
        })
        return eng

    # --- forward construction ---

    def _make_forward_body(self):
        """Full-graph logits [W, n_pad, C] — the exact shard_map body
        ``make_eval_step`` runs up to (not including) its loss/metrics."""
        model, batch_args, mesh = self.model, self.batch_args, self.mesh
        batch_specs, plan_specs = self._batch_specs, self._plan_specs

        def shard_body(params, batch, plan):
            p = squeeze_plan(plan)
            b = jax.tree.map(lambda leaf: leaf[0], batch)
            return model_apply(model, params, b, p, batch_args)[None]

        def full(params, batch, plan):
            from dgraph_tpu.comm.collectives import shard_map_checks

            return jax.shard_map(
                shard_body,
                mesh=mesh,
                in_specs=(P(), batch_specs, plan_specs),
                out_specs=P(GRAPH_AXIS),
                # pallas_p2p forwards relax the 0.4.x rep checker
                # (pallas_call has no replication rule there)
                **shard_map_checks(plan, GRAPH_AXIS),
            )(params, batch, plan)

        return full

    def _build_forward(self):
        full = self._make_forward_body()

        def fwd(params, batch, plan, rank_idx, slot_idx):
            # full forward + [bucket]-row gather in ONE program: the gather
            # shape is the only thing that varies across buckets, and the
            # index operands are per-request scratch — donated
            return full(params, batch, plan)[rank_idx, slot_idx]

        return jax.jit(fwd, donate_argnums=(3, 4))

    # --- hot path ---

    def infer(self, node_ids, _record: bool = True) -> np.ndarray:
        """Logits [n, num_classes] for ``node_ids`` (original numbering).

        Pads to the request's bucket, replays that bucket's executable, and
        slices the padding back off. Raises
        :class:`~dgraph_tpu.serve.errors.RequestTooLarge` past the ladder
        and ValueError on out-of-range ids.

        Self-healing: a transient device error is retried (same cached
        executable — a retry can never compile) up to ``max_retries``
        times with a short backoff; ``degrade_after`` consecutive
        retry-exhausted requests flip the engine into DEGRADED mode, where
        every request is shed fast with the structured
        :class:`~dgraph_tpu.serve.errors.QueueFull` until
        :meth:`reset_degraded`. The ``serve.infer`` chaos point
        (:mod:`dgraph_tpu.chaos`) fires inside the retried section, which
        is how both paths are tested deterministically.
        """
        from dgraph_tpu import chaos
        from dgraph_tpu.serve.errors import QueueFull, ServeError

        ids = np.asarray(node_ids)
        if ids.ndim != 1:
            raise ValueError(f"node_ids must be 1-D, got shape {ids.shape}")
        # ONE coherent control-plane snapshot under the engine lock: the
        # degraded flag, the failure epoch, the id maps, and the batch
        # reference all come from the same swap/append generation — and
        # the lock is never held across a device dispatch.  Piecemeal
        # unlocked reads here raced swap_params/append_vertices/
        # reset_degraded (host-lock-discipline; pinned in
        # tests/test_analysis_host.py).
        with self._lock:
            degraded = self.degraded
            consecutive = self._consecutive_failures
            # failure-epoch snapshot: if reset_degraded() lands while
            # this request is in flight, its eventual failure belongs to
            # the OLD epoch and must not count toward (or resurrect)
            # degraded mode
            epoch = self._failure_epoch
            id_rank, id_slot = self._id_rank, self._id_slot
            num_nodes = self.num_nodes
            params, batch, plan = self._params, self._batch, self._plan
        if ids.size and (ids.min() < 0 or ids.max() >= num_nodes):
            raise ValueError(
                f"node ids must be in [0, {num_nodes}), got "
                f"[{ids.min()}, {ids.max()}]"
            )
        # span parent = the batcher's ambient batch span when called from
        # the worker thread (contextvar), a root otherwise; one attr read
        # when tracing is off. The SAME span covers every retry, so the
        # trace id survives the retry/degraded paths.
        sp = spans.span("serve.infer", n=int(ids.shape[0]))
        if degraded:
            self.registry.counter("serve.shed_degraded")
            sp.end(error="backpressure: degraded shed")
            raise QueueFull(
                "engine degraded after repeated device failures; shedding "
                "load (reset_degraded() to re-admit)",
                degraded=True,
                consecutive_failures=consecutive,
            )
        t0 = time.perf_counter()
        try:
            bucket = self.ladder.bucket_for(ids.shape[0])
        except ServeError as e:  # RequestTooLarge: structured, never queued
            sp.end(error=e.code)
            raise
        padded, n = pad_ids(ids, bucket)
        # pad stage: bucket pick + id padding + the FIRST index-operand
        # build (rebuilds inside the retry loop are failure-path cost and
        # stay inside the infer stage)
        rank_idx = jnp.asarray(id_rank[padded])
        slot_idx = jnp.asarray(id_slot[padded])
        pad_ms = (time.perf_counter() - t0) * 1e3
        t_infer = time.perf_counter()
        last_err = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                # index operands are rebuilt per retry: they are DONATED to
                # the executable, and a dispatch that failed midway may
                # already have invalidated them
                rank_idx = jnp.asarray(id_rank[padded])
                slot_idx = jnp.asarray(id_slot[padded])
            try:
                chaos.fire("serve.infer")
                with jax.set_mesh(self.mesh):
                    out = self._forwards[bucket](
                        params, batch, plan, rank_idx, slot_idx,
                    )
                out = np.asarray(jax.block_until_ready(out))[:n]
                break
            except ServeError:  # structured rejections are never transient
                sp.end(error="serve_error", attempts=attempt + 1)
                raise
            except Exception as e:  # noqa: BLE001 — transient device error
                last_err = e
                if attempt < self.max_retries:
                    self.registry.counter("serve.infer_retries")
                    time.sleep(self.retry_backoff_s)
        else:
            degraded_now = False
            with self._lock:
                if epoch == self._failure_epoch:
                    self._consecutive_failures += 1
                    consecutive = self._consecutive_failures
                    if (
                        self._consecutive_failures >= self.degrade_after
                        and not self.degraded
                    ):
                        self.degraded = True
                        degraded_now = True
            self.registry.counter("serve.infer_failures")
            if degraded_now:
                self.registry.gauge("serve.degraded", 1.0)
                print(
                    f"[serve] engine DEGRADED after "
                    f"{consecutive} consecutive infer "
                    f"failures (last: {type(last_err).__name__}: {last_err})",
                    flush=True,
                )
            sp.end(
                error=f"{type(last_err).__name__}: {last_err}",
                attempts=self.max_retries + 1,
            )
            raise last_err
        with self._lock:
            if epoch == self._failure_epoch:
                self._consecutive_failures = 0
        infer_ms = (time.perf_counter() - t_infer) * 1e3
        # per-stage timings for the batcher's request spans + health
        # quantiles (worker-thread single-writer; read right after infer)
        self.last_stage_ms = {"pad": pad_ms, "infer": infer_ms}
        sp.end(bucket=int(bucket), pad_ms=round(pad_ms, 3),
               infer_ms=round(infer_ms, 3))
        if _record:
            dt_ms = (time.perf_counter() - t0) * 1e3
            reg = self.registry
            reg.counter("serve.infer_calls")
            reg.histogram("serve.infer_ms", dt_ms)
            reg.histogram("serve.stage.pad_ms", pad_ms)
            reg.histogram("serve.stage.infer_ms", infer_ms)
            reg.histogram("serve.batch_occupancy", n / bucket)
            reg.gauge(
                "serve.recompiles_since_warmup",
                float(self.recompiles_since_warmup()),
            )
        return out

    def reset_degraded(self) -> None:
        """Re-admit traffic after a degraded period (the operator's — or a
        health-checker's — explicit decision: auto-undegrading would flap
        against a still-dead backend).

        Atomic against the batcher worker: state flips under the engine
        lock, and bumping the failure epoch makes any infer that was
        DISPATCHED before this reset report its failure into the old epoch
        — a concurrent failure can no longer resurrect degraded mode (or
        spend the fresh degrade window) the instant after an operator
        re-admitted traffic."""
        with self._lock:
            self._failure_epoch += 1
            self.degraded = False
            self._consecutive_failures = 0
        self.registry.gauge("serve.degraded", 0.0)

    # --- control plane: hot-swap rollover + live vertex appends ---

    def swap_params(self, source=None, *, step: Optional[int] = None,
                    params=None, parity_ids=None) -> dict:
        """Hot-swap to a newly restored checkpoint under the SAME warmed
        executables — zero recompiles, atomic per batch, automatic
        rollback on a bad checkpoint.

        ``source`` is a checkpoint directory (``step`` picks a step;
        default newest readable), defaulting to the engine's own
        :attr:`ckpt_dir`; or pass an explicit ``params`` tree. The staged
        params are validated BEFORE the live pointer moves — structure/
        shape/dtype against the warmed executables, host-side non-finite
        guard, and the served==eval parity oracle run *with the staged
        tree as an argument* through the already-compiled forwards — so a
        rejected swap (:class:`~dgraph_tpu.serve.errors.SwapRejected`)
        leaves the prior params serving without a single dropped request.
        See :func:`dgraph_tpu.serve.rollover.swap_params` for the full
        state machine; every attempt lands one record in :attr:`lineage`.
        """
        from dgraph_tpu.serve.rollover import swap_params as _swap

        return _swap(self, source, step=step, params=params,
                     parity_ids=parity_ids)

    def free_pad_slots(self) -> int:
        """Reserved pad capacity left for live vertex appends before the
        next re-plan must rebuild (``serve.deltas.replan``); 0 when the
        engine has no appendable batch."""
        # _host_x/_slot_fill are append_vertices' locked state; the lock
        # is reentrant, so the in-lock error-message call below still
        # works (host-lock-discipline)
        with self._lock:
            if self._host_x is None:
                return 0
            return int((self._host_x.shape[1] - self._slot_fill).sum())

    def append_vertices(self, features) -> np.ndarray:
        """Install new vertices into reserved pad slots, live — returns
        their (original-numbering) ids, ``num_nodes .. num_nodes+k``.

        The appended vertices are queryable immediately: their features
        enter the sharded batch, their vertex mask flips to 1.0, and the
        id map grows — all flipped in ONE reference assignment under the
        engine lock, so a concurrent batch sees entirely the old or
        entirely the new graph. Shapes never change (the rows were already
        padded), so the warmed executables replay untouched. Edges
        incident to appended vertices are NOT live until a background
        re-plan is adopted (:mod:`dgraph_tpu.serve.deltas`): until then an
        appended vertex aggregates nothing — exactly an isolated vertex.
        Raises ValueError when the pad budget is exhausted (the signal to
        re-plan)."""
        with self._lock:
            # validation INSIDE the lock too: the shape/dtype checks read
            # _host_x, which a concurrent append is allowed to replace
            # (host-lock-discipline); RLock keeps the nested
            # free_pad_slots() call below legal
            if self._host_x is None:
                raise ValueError(
                    "engine batch has no 'x' leaf to append into"
                )
            feats = np.asarray(features, self._host_x.dtype)
            if feats.ndim != 2 or feats.shape[1] != self._host_x.shape[2]:
                raise ValueError(
                    f"features must be [k, {self._host_x.shape[2]}], got "
                    f"{feats.shape}"
                )
            k = int(feats.shape[0])
            n_pad = self._host_x.shape[1]
            if k > int((n_pad - self._slot_fill).sum()):
                raise ValueError(
                    f"{k} new vertices exceed the {self.free_pad_slots()} "
                    "free pad slots; adopt a re-planned generation first "
                    "(serve.deltas.replan)"
                )
            from dgraph_tpu.serve.deltas import assign_new_vertices

            # deterministic waterfill SHARED with serve.deltas.replan:
            # the background rebuild replays the same placement, so
            # adoption never moves a vertex already served from a pad slot
            fill = self._slot_fill.copy()
            new_rank = assign_new_vertices(fill, k)
            new_slot = np.empty(k, np.int32)
            running = self._slot_fill.copy()
            for i, r in enumerate(new_rank):
                new_slot[i] = running[r]
                running[r] += 1
            # place_like: the SAME placement contract the rollover staging
            # uses (mirror multi-device shardings, keep single-device
            # leaves uncommitted) — shared so the two paths cannot drift
            from dgraph_tpu.serve.rollover import place_like

            x2 = self._host_x.copy()
            x2[new_rank, new_slot] = feats
            batch2 = dict(self._batch)
            batch2["x"] = place_like(x2, self._batch["x"])
            if self._host_vmask is not None:
                vm2 = self._host_vmask.copy()
                vm2[new_rank, new_slot] = 1.0
                batch2["vmask"] = place_like(vm2, self._batch["vmask"])
                self._host_vmask = vm2
            ids = np.arange(self.num_nodes, self.num_nodes + k, dtype=np.int64)
            # the flip: one reference assignment each — infer reads
            # self._batch / the id maps once per dispatch
            self._host_x = x2
            self._batch = batch2
            self._id_rank = np.concatenate([self._id_rank, new_rank])
            self._id_slot = np.concatenate([self._id_slot, new_slot])
            self._slot_fill = fill
            self.num_nodes += k
        self.registry.counter("serve.vertices_appended", float(k))
        return ids

    def rank_slot(self, node_ids) -> tuple:
        """(rank, slot) arrays for original vertex ids — the row addresses
        of those vertices in any ``[W, n_pad, ...]`` sharded tensor (e.g.
        :meth:`full_logits`)."""
        # one locked snapshot: append_vertices grows both maps together,
        # and an unlocked pair of reads could see one grown and one not
        # (host-lock-discipline)
        with self._lock:
            id_rank, id_slot = self._id_rank, self._id_slot
        ids = np.asarray(node_ids)
        return id_rank[ids], id_slot[ids]

    def full_logits(self) -> np.ndarray:
        """[W, n_pad, C] logits for the whole graph — the parity oracle the
        selftest checks the bucketed path against bit-for-bit, and the bulk
        (batch-scoring) escape hatch. Row (r, s) serves original vertex id
        with ``id_rank==r, id_slot==s``."""
        # same snapshot discipline as infer: one locked read of the
        # swap/append-mutable references, lock released before dispatch
        with self._lock:
            params, batch, plan = self._params, self._batch, self._plan
        with jax.set_mesh(self.mesh):
            out = self._full(params, batch, plan)
        return np.asarray(jax.block_until_ready(out))

    # --- warmup / recompile accounting ---

    def warmup(self) -> dict:
        """Ahead-of-time compile every bucket so the hot path never does.

        Each bucket runs twice: the first call's outputs carry mesh
        shardings its fresh host inputs did not, which legitimately earns
        any jitted step one extra compile (same effect pinned in
        tests/test_obs.py) — warming twice reaches the steady-state cache
        before the baseline is recorded. Returns a summary record.
        """
        t0 = time.perf_counter()
        for b in self.ladder.sizes:
            ids = np.zeros(b, np.int64)
            for _ in range(2):
                self.infer(ids, _record=False)
        # the full-logits oracle counts toward _total_compiles too — warm it
        # so a post-warmup parity check can't read as a hot-path recompile
        for _ in range(2):
            self.full_logits()
        self.warmup_s = round(time.perf_counter() - t0, 3)
        self._compiles_at_warmup = self._total_compiles()
        self.registry.gauge("serve.warmup_s", self.warmup_s)
        self.registry.gauge("serve.recompiles_since_warmup", 0.0)
        return {
            "kind": "serve_warmup",
            "buckets": [int(b) for b in self.ladder.sizes],
            "warmup_s": self.warmup_s,
            "compiles_at_warmup": self._compiles_at_warmup,
        }

    def _total_compiles(self) -> int:
        """Sum of jit-cache entries across the bucket forwards (plus the
        full-logits oracle). ``_cache_size`` is jax-private but present on
        0.4-0.6; if a future jax drops it the counter degrades to 0 rather
        than breaking serving."""
        total = 0
        for f in (*self._forwards.values(), self._full):
            cache_size = getattr(f, "_cache_size", None)
            if cache_size is not None:
                total += int(cache_size())
        return total

    def recompiles_since_warmup(self) -> int:
        """XLA compiles after :meth:`warmup` returned — the serving SLO
        invariant is that this stays 0 in steady state. Before warmup,
        every compile counts (a cold hot-path compile is exactly what the
        counter exists to expose)."""
        base = self._compiles_at_warmup or 0
        return max(0, self._total_compiles() - base)
