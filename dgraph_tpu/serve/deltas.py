"""Live graph deltas: pad-slot appends, background re-plan, atomic adoption.

A serving graph is not static — new vertices and edges arrive while the
fleet is under traffic, and "rebuild everything and restart" drops
requests and re-pays the warmup. This module splits graph growth into a
fast live half and a durable background half, glued by the same
generation-pointer discipline the shrink-to-fit recovery proved
(:mod:`dgraph_tpu.train.shrink`):

- **Append (live, bounded).** Every plan already pads each rank's vertex
  block to ``n_pad``; the slack above the real count is *reserved
  capacity*. :func:`append_delta` makes the new vertices/edges durable
  (one atomic npz per append, staged against the current generation), and
  :meth:`~dgraph_tpu.serve.engine.ServeEngine.append_vertices` installs
  the vertices into those pad slots on the running engine — queryable
  immediately, zero shape changes, zero recompiles. New *edges* stay
  staged (the plan's routing is static) until the next adoption: an
  appended vertex serves as an isolated vertex until then.

- **Re-plan (background, resumable).** :func:`replan` composes the base
  graph with every staged delta, re-partitions the new vertices with the
  SAME deterministic waterfill the live append used (placement is
  preserved, so adoption moves no already-served vertex), and rebuilds
  the sharded plan artifact for generation ``g+1`` through the streaming
  :func:`~dgraph_tpu.plan.build_plan_shards` (memory-budgeted, durable
  per shard, resumable after a kill).

- **Adopt (atomic).** Only after the new generation's plan and graph
  snapshot are fully durable does the ``serving.json`` pointer flip — one
  atomic rename (:func:`~dgraph_tpu.plan_shards.atomic_write_json`). A
  crash ANYWHERE leaves the old or the new generation adopted, never a
  torn mix (chaos-pinned via ``serve.replan=sigterm``). The serving
  process then builds a fresh engine for the generation
  (:func:`build_engine`), warms it off-path, and flips it live through
  :meth:`~dgraph_tpu.serve.registry.ModelRegistry.activate` — in-flight
  batches finish on the old engine, the next batch runs on the new one.

Layout under one run directory::

    run_dir/
      serving.json          <- THE adoption pointer {generation, ...}
      graph_g0.npz          <- original-numbering edges+features+partition
      plan_g0/              <- v8 sharded plan artifact (manifest+shards)
      deltas_g0/            <- staged appends AGAINST generation 0
        delta_0000.npz
      graph_g1.npz  plan_g1/  deltas_g1/   <- next generation, same shape
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

import numpy as np

SERVE_POINTER = "serving.json"

# per-run_dir append/adopt serialization for THIS process (the owning
# serving process runs appends on request threads and replan in a
# background thread); cross-process append collisions are additionally
# closed by the no-clobber link publish in append_delta
_RUN_LOCKS: dict = {}
_RUN_LOCKS_GUARD = threading.Lock()


def _run_lock(run_dir: str) -> threading.Lock:
    key = os.path.abspath(run_dir)
    with _RUN_LOCKS_GUARD:
        lock = _RUN_LOCKS.get(key)
        if lock is None:
            lock = _RUN_LOCKS[key] = threading.Lock()
        return lock


class DeltaError(RuntimeError):
    """A delta append or generation transition could not complete."""

    def __init__(self, reason: str):
        super().__init__(f"serve graph-delta failure: {reason}")
        self.reason = reason

    def record(self) -> dict:
        return {"kind": "serve_delta_error", "reason": self.reason}


# ---------------------------------------------------------------------------
# generation layout (ONE place derives every path — the shrink.py discipline)
# ---------------------------------------------------------------------------


def world_path(run_dir: str) -> str:
    return os.path.join(run_dir, SERVE_POINTER)


def plan_dir(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"plan_g{generation}")


def graph_path(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"graph_g{generation}.npz")


def delta_dir(run_dir: str, generation: int) -> str:
    return os.path.join(run_dir, f"deltas_g{generation}")


def read_world(run_dir: str) -> dict:
    """The current adoption pointer; raises :class:`DeltaError` when the
    run directory holds none (the atomic write makes a torn pointer real
    corruption, not a benign race)."""
    path = world_path(run_dir)
    try:
        with open(path) as fh:
            rec = json.load(fh)
    except OSError as e:
        raise DeltaError(f"no serving pointer at {path} ({e})")
    except ValueError as e:
        raise DeltaError(f"serving pointer {path} unreadable: {e}")
    if rec.get("kind") != "serve_world":
        raise DeltaError(f"{path} is not a serve_world record")
    return rec


def write_world(run_dir: str, rec: dict) -> None:
    """ATOMIC adoption: the rename is the commit point of a generation
    transition."""
    from dgraph_tpu.plan_shards import atomic_write_json

    atomic_write_json(world_path(run_dir), rec)


def _atomic_savez(path: str, **arrays) -> None:
    # ONE fsync+rename savez, shared with train/shrink.py (the local
    # tmp+replace this used to hand-roll skipped the fsync — the rename
    # could commit before the bytes; host-durable-write now enforces the
    # shared writer)
    from dgraph_tpu.plan_shards import atomic_savez

    atomic_savez(path, **arrays)


# ---------------------------------------------------------------------------
# deterministic new-vertex placement (shared with ServeEngine.append_vertices)
# ---------------------------------------------------------------------------


def assign_new_vertices(fill: np.ndarray, k: int) -> np.ndarray:
    """Rank assignment for ``k`` appended vertices over per-rank occupancy
    ``fill`` (mutated in place): least-filled rank first, lowest rank id
    on ties. Deterministic on purpose — the live append and the
    background re-plan replay the SAME placement, so adoption never moves
    a vertex that is already being served from its pad slot's rank."""
    fill = np.asarray(fill)
    ranks = np.empty(k, np.int32)
    for i in range(k):
        r = int(np.argmin(fill))
        ranks[i] = r
        fill[r] += 1
    return ranks


# ---------------------------------------------------------------------------
# world lifecycle
# ---------------------------------------------------------------------------


def init_world(
    run_dir: str,
    edge_index: np.ndarray,
    features: np.ndarray,
    *,
    world_size: int,
    partition_method: str = "random",
    seed: int = 0,
    pad_multiple: int = 8,
    memory_budget_bytes: Optional[int] = None,
) -> dict:
    """Create generation 0 of a delta-capable serving world: partition the
    graph, build the sharded plan artifact, snapshot the graph in its
    ORIGINAL numbering, adopt ``serving.json``. Idempotent on rerun (the
    plan build resumes; the pointer write is last)."""
    from dgraph_tpu.partition import partition_graph
    from dgraph_tpu.plan import build_plan_shards

    os.makedirs(run_dir, exist_ok=True)
    edge_index = np.asarray(edge_index)
    features = np.asarray(features, np.float32)
    num_nodes = int(features.shape[0])
    new_edges, ren = partition_graph(
        edge_index, num_nodes, world_size, method=partition_method,
        seed=seed,
    )
    part_orig = np.asarray(ren.partition)[np.asarray(ren.perm)]
    _atomic_savez(
        graph_path(run_dir, 0),
        edge_index=edge_index,  # ORIGINAL numbering: deltas append to it
        features=features,
        partition=part_orig,
    )
    build_plan_shards(
        new_edges, ren.partition, out_dir=plan_dir(run_dir, 0),
        world_size=world_size, pad_multiple=pad_multiple,
        write_layout=True, memory_budget_bytes=memory_budget_bytes,
    )
    rec = {
        "kind": "serve_world",
        "generation": 0,
        "world_size": int(world_size),
        "num_nodes": num_nodes,
        "num_edges": int(edge_index.shape[1]),
        "feat_dim": int(features.shape[1]),
        "pad_multiple": int(pad_multiple),
        "partition_method": partition_method,
        "seed": int(seed),
        "deltas_adopted": 0,
    }
    write_world(run_dir, rec)
    return rec


# ---------------------------------------------------------------------------
# staged deltas
# ---------------------------------------------------------------------------


def staged_delta_paths(run_dir: str, generation: int) -> list:
    d = delta_dir(run_dir, generation)
    if not os.path.isdir(d):
        return []
    return sorted(
        os.path.join(d, f) for f in os.listdir(d)
        if f.startswith("delta_") and f.endswith(".npz")
    )


def append_delta(run_dir: str, features, edge_index) -> dict:
    """Durably stage new vertices (+ their edges, which may reference any
    existing or just-appended vertex) against the current generation.
    Returns the structured record, including ``id_base`` — the original
    ids of the appended vertices are ``id_base .. id_base+k``.

    Durability order matters: stage here FIRST, then install live with
    ``engine.append_vertices`` — a crash between the two replays the
    append from disk at the next re-plan instead of losing it. The
    ``serve.delta_append`` chaos point fires at entry."""
    from dgraph_tpu import chaos

    chaos.fire("serve.delta_append")
    feats = np.asarray(features, np.float32)
    edges = np.asarray(edge_index, np.int64)
    if edges.size and (edges.ndim != 2 or edges.shape[0] != 2):
        raise DeltaError(f"delta edge_index must be [2, m], got {edges.shape}")
    edges = edges.reshape(2, -1)
    k = int(feats.shape[0])
    with _run_lock(run_dir):
        # under the lock: the pointer read, the seq/id_base derivation,
        # and the publish are one atomic step against this process's
        # other appenders AND against replan's commit (which re-snapshots
        # under the same lock before flipping the pointer)
        world = read_world(run_dir)
        gen = int(world["generation"])
        if feats.ndim != 2 or feats.shape[1] != int(world["feat_dim"]):
            raise DeltaError(
                f"delta features must be [k, {world['feat_dim']}], got "
                f"{feats.shape}"
            )
        os.makedirs(delta_dir(run_dir, gen), exist_ok=True)
        while True:
            existing = staged_delta_paths(run_dir, gen)
            if existing:
                # O(1) per append: every delta file stamps its own
                # id_base + new_nodes scalars, so the NEXT base reads one
                # file's scalars instead of decompressing every staged
                # features array
                last = np.load(existing[-1])
                id_base = int(last["id_base"]) + int(last["new_nodes"])
            else:
                id_base = int(world["num_nodes"])
            if edges.size and (
                edges.min() < 0 or edges.max() >= id_base + k
            ):
                raise DeltaError(
                    f"delta edges reference vertex ids outside "
                    f"[0, {id_base + k})"
                )
            seq = len(existing)
            path = os.path.join(
                delta_dir(run_dir, gen), f"delta_{seq:04d}.npz"
            )
            tmp = path + ".tmp.npz"
            np.savez(
                tmp, features=feats, edge_index=edges,
                id_base=np.int64(id_base), new_nodes=np.int64(k),
            )
            try:
                # no-clobber publish: link() fails (instead of silently
                # overwriting like os.replace) if ANOTHER process raced
                # this seq — on collision, recompute seq/id_base and retry
                os.link(tmp, path)
                os.unlink(tmp)
                break
            except FileExistsError:
                os.unlink(tmp)
    return {
        "kind": "serve_delta",
        "generation": gen,
        "seq": seq,
        "new_nodes": k,
        "new_edges": int(edges.shape[1]),
        "id_base": id_base,
    }


# ---------------------------------------------------------------------------
# background re-plan + atomic adoption
# ---------------------------------------------------------------------------


def replan(
    run_dir: str, *, memory_budget_bytes: Optional[int] = None,
    max_rounds: int = 5,
) -> dict:
    """Fold every staged delta into generation ``g+1`` and adopt it.

    Crash-safe and rerunnable, mirroring ``shrink_world``: all artifacts
    are written under the NEW generation's names (the old generation stays
    intact and adopted throughout), the streaming plan build resumes from
    its own manifest, the graph snapshot write is atomic, and the
    ``serving.json`` flip is the single commit point. The ``serve.replan``
    chaos point fires at entry (before any build work) and again at each
    commit boundary after every artifact is durable but before the pointer
    flips — so both torn windows are deterministically testable.

    Append-safe: the commit re-snapshots the staged set under the same
    lock ``append_delta`` publishes under — a delta that landed while the
    build ran is never orphaned; the fold runs another round including it
    (up to ``max_rounds``, then a structured :class:`DeltaError` tells the
    operator to quiesce appends) and only a build whose input set is still
    current adopts.

    Memory note: the COMPOSITION is whole-graph-resident on the host
    (base features/edges + staged deltas are concatenated before the
    build); ``memory_budget_bytes`` bounds the plan build's per-shard
    peak, not this composition step.

    With nothing staged this is a no-op returning the current pointer.
    """
    from dgraph_tpu import chaos
    from dgraph_tpu.obs import spans
    from dgraph_tpu.partition import renumber_contiguous
    from dgraph_tpu.plan import build_plan_shards

    world = read_world(run_dir)
    gen, W = int(world["generation"]), int(world["world_size"])
    chaos.fire("serve.replan")
    delta_paths = staged_delta_paths(run_dir, gen)
    if not delta_paths:
        return world
    with spans.span(
        "serve.replan", run_dir=run_dir, generation=gen + 1,
        deltas=len(delta_paths),
    ):
        for _round in range(max_rounds):
            base = np.load(graph_path(run_dir, gen))
            part = np.asarray(base["partition"])
            fill = np.bincount(part, minlength=W).astype(np.int64)
            feats = [np.asarray(base["features"])]
            edges = [np.asarray(base["edge_index"])]
            parts = [part]
            for p in delta_paths:
                d = np.load(p)
                k = int(d["features"].shape[0])
                # the SAME waterfill the live append ran
                # (assign_new_vertices mutates fill), so placement
                # composes identically
                parts.append(assign_new_vertices(fill, k))
                feats.append(np.asarray(d["features"]))
                edges.append(np.asarray(d["edge_index"]))
            partition_full = np.concatenate(parts)
            features_full = np.concatenate(feats)
            edges_full = np.concatenate(edges, axis=1)
            V_new = int(partition_full.shape[0])
            ren = renumber_contiguous(partition_full, W)
            new_edges = np.asarray(ren.perm)[edges_full]
            build_plan_shards(
                new_edges, ren.partition,
                out_dir=plan_dir(run_dir, gen + 1),
                world_size=W, pad_multiple=int(world.get("pad_multiple", 8)),
                write_layout=True, memory_budget_bytes=memory_budget_bytes,
            )
            _atomic_savez(
                graph_path(run_dir, gen + 1),
                edge_index=edges_full,
                features=features_full,
                partition=partition_full,
            )
            # every artifact is durable; the pointer flip below is the
            # commit — a sigterm injected HERE must leave generation g
            # adopted
            chaos.fire("serve.replan")
            with _run_lock(run_dir):
                latest = staged_delta_paths(run_dir, gen)
                if latest == delta_paths:
                    rec = {
                        **world,
                        "generation": gen + 1,
                        "num_nodes": V_new,
                        "num_edges": int(edges_full.shape[1]),
                        "deltas_adopted": int(world.get("deltas_adopted", 0))
                        + len(delta_paths),
                    }
                    write_world(run_dir, rec)
                    return rec
            # a delta landed mid-build: adopting now would orphan it (the
            # next generation only ever reads its OWN staged dir) — fold
            # again with the grown set
            delta_paths = latest
        raise DeltaError(
            f"staged deltas kept arriving across {max_rounds} replan "
            "rounds; quiesce appends (or raise max_rounds) to adopt"
        )


# ---------------------------------------------------------------------------
# loading an adopted generation into a serving engine
# ---------------------------------------------------------------------------


def load_generation(run_dir: str, *, verify: bool = True) -> dict:
    """Everything a :class:`~dgraph_tpu.serve.engine.ServeEngine` needs
    for the currently adopted generation: assembled plan + layout (from
    the v8 shard artifact), vertex-sharded batch, original-id -> (rank,
    slot) maps."""
    from dgraph_tpu.partition import renumber_contiguous
    from dgraph_tpu.plan import load_sharded_plan, shard_vertex_data

    world = read_world(run_dir)
    gen, W = int(world["generation"]), int(world["world_size"])
    plan, layout = load_sharded_plan(plan_dir(run_dir, gen), verify=verify)
    graph = np.load(graph_path(run_dir, gen))
    part = np.asarray(graph["partition"])
    V = int(part.shape[0])
    ren = renumber_contiguous(part, W)
    n_pad = int(plan.n_src_pad)
    feats = shard_vertex_data(
        np.asarray(graph["features"])[ren.inv], ren.counts, n_pad
    ).astype(np.float32)
    vmask = shard_vertex_data(np.ones(V, np.float32), ren.counts, n_pad)
    id_rank = np.asarray(ren.partition)[np.asarray(ren.perm)]
    id_slot = np.asarray(ren.perm) - np.asarray(ren.offsets)[id_rank]
    return {
        "world": world,
        "generation": gen,
        "plan": plan,
        "layout": layout,
        "edge_index": np.asarray(graph["edge_index"]),
        "batch": {"x": feats, "vmask": vmask},
        "id_rank": id_rank.astype(np.int32),
        "id_slot": id_slot.astype(np.int32),
        "num_nodes": V,
    }


def build_engine(
    run_dir: str,
    model,
    mesh,
    params,
    *,
    add_symmetric_norm: bool = False,
    verify: bool = True,
    **engine_kwargs,
):
    """A fresh (unwarmed) engine over the adopted generation — the object
    a :class:`~dgraph_tpu.serve.registry.ModelRegistry` activates after a
    re-plan. Params are the caller's (adoption changes the graph, not the
    checkpoint; run :meth:`~dgraph_tpu.serve.engine.ServeEngine.
    swap_params` separately for that)."""
    from dgraph_tpu.data.graph import symmetric_norm_weights
    from dgraph_tpu.plan import shard_edge_data
    from dgraph_tpu.serve.engine import ServeEngine

    info = load_generation(run_dir, verify=verify)
    batch = dict(info["batch"])
    if add_symmetric_norm:
        from dgraph_tpu.partition import renumber_contiguous

        graph = np.load(graph_path(run_dir, info["generation"]))
        ren = renumber_contiguous(
            np.asarray(graph["partition"]),
            int(info["world"]["world_size"]),
        )
        new_edges = np.asarray(ren.perm)[info["edge_index"]]
        w = symmetric_norm_weights(new_edges, info["num_nodes"])
        batch["edge_weight"] = shard_edge_data(
            w, info["layout"], int(info["plan"].e_pad)
        )
    eng = ServeEngine(
        model, mesh, info["plan"], params, batch,
        info["id_rank"], info["id_slot"], **engine_kwargs,
    )
    eng.generation = info["generation"]
    return eng
