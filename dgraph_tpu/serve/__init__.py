"""Online GNN inference serving over a partitioned graph.

The training stack ends at ``make_eval_step``; this package is the path
from a checkpoint to request/response inference at production latency on
TPU, built on the one discipline that matters there — **no XLA compiles on
the hot path**:

- :mod:`~dgraph_tpu.serve.bucketing` — requests are padded up a small
  geometric ladder of target-node-count buckets (:class:`BucketLadder`),
  so every request shape is one of a handful compiled ahead of time.
- :mod:`~dgraph_tpu.serve.engine` — :class:`ServeEngine` restores params
  (``train.checkpoint.restore_checkpoint``), holds one jitted, donated
  forward per bucket (the same ``train.loop.model_apply`` forward the
  train/eval steps run), AOT-warms every bucket, and counts recompiles
  (steady state == 0, pinned by ``--selftest``).
- :mod:`~dgraph_tpu.serve.batcher` — :class:`MicroBatcher` coalesces
  concurrent requests into one padded call: bounded queue with structured
  backpressure (:class:`~dgraph_tpu.serve.errors.QueueFull`), bounded batch
  delay, per-request deadlines.
- :mod:`~dgraph_tpu.serve.health` — the ``serve_health`` JSONL record
  (latency percentiles, queue state, recompile counter) riding the
  :mod:`dgraph_tpu.obs` pipeline.

CLI: ``python -m dgraph_tpu.serve --selftest`` is the single-process CPU
end-to-end check; ``experiments/serve_bench.py`` is the closed-loop load
generator.
"""

from dgraph_tpu.serve.batcher import MicroBatcher
from dgraph_tpu.serve.bucketing import BucketLadder, pad_ids
from dgraph_tpu.serve.engine import ServeEngine
from dgraph_tpu.serve.errors import (
    EngineStopped,
    QueueFull,
    RequestTimeout,
    RequestTooLarge,
    ServeError,
)
from dgraph_tpu.serve.health import serve_health_record

__all__ = [
    "BucketLadder",
    "EngineStopped",
    "MicroBatcher",
    "QueueFull",
    "RequestTimeout",
    "RequestTooLarge",
    "ServeEngine",
    "ServeError",
    "pad_ids",
    "serve_health_record",
]
