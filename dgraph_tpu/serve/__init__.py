"""Online GNN inference serving over a partitioned graph.

The training stack ends at ``make_eval_step``; this package is the path
from a checkpoint to request/response inference at production latency on
TPU, built on the one discipline that matters there — **no XLA compiles on
the hot path** — plus the control plane that keeps a fleet serving through
change:

- :mod:`~dgraph_tpu.serve.bucketing` — requests are padded up a small
  geometric ladder of target-node-count buckets (:class:`BucketLadder`),
  so every request shape is one of a handful compiled ahead of time.
- :mod:`~dgraph_tpu.serve.engine` — :class:`ServeEngine` restores params
  (``train.checkpoint.restore_checkpoint``), holds one jitted, donated
  forward per bucket (the same ``train.loop.model_apply`` forward the
  train/eval steps run), AOT-warms every bucket, and counts recompiles
  (steady state == 0, pinned by ``--selftest``).
- :mod:`~dgraph_tpu.serve.batcher` — :class:`MicroBatcher` coalesces
  concurrent requests into one padded call: bounded queue with structured
  backpressure (:class:`~dgraph_tpu.serve.errors.QueueFull`), bounded batch
  delay, per-request deadlines, per-tenant admission.
- :mod:`~dgraph_tpu.serve.rollover` — hot-swap checkpoint rollover under
  the same warmed executables (:meth:`ServeEngine.swap_params`): zero
  recompiles, per-batch atomic, automatic rollback on a bad checkpoint.
- :mod:`~dgraph_tpu.serve.registry` — :class:`ModelRegistry`: named
  model/graph versions behind one batcher, activated atomically between
  batches.
- :mod:`~dgraph_tpu.serve.tenancy` — :class:`TenantTable`: token-bucket
  rate quotas, bounded queue shares, per-tenant degraded shedding.
- :mod:`~dgraph_tpu.serve.deltas` — live graph growth: pad-slot vertex
  appends, background streaming re-plan, atomic generation-pointer
  adoption.
- :mod:`~dgraph_tpu.serve.health` — the ``serve_health`` JSONL record
  (latency percentiles, queue/tenant state, lineage, recompile counter)
  riding the :mod:`dgraph_tpu.obs` pipeline.

Module-level imports here are LAZY (PEP 562 ``__getattr__``) on purpose:
the control-plane bookkeeping (``registry``/``tenancy``/``errors``) is
under the ``jax-free-module`` lint contract so the train supervisor and
health tooling can import it in processes that never dial a backend — an
eager ``from dgraph_tpu.serve.engine import ServeEngine`` here would drag
jax into every one of those imports. Call sites keep working unchanged
through the lazy hook.

CLI: ``python -m dgraph_tpu.serve --selftest`` is the single-process CPU
end-to-end check (traffic + hot-swap + quota paths, compile-free);
``experiments/serve_bench.py`` is the load generator (closed-loop and
multi-tenant open-loop).
"""

from __future__ import annotations

__all__ = [
    "BucketLadder",
    "EngineStopped",
    "MicroBatcher",
    "ModelRegistry",
    "QueueFull",
    "QuotaExceeded",
    "RequestTimeout",
    "RequestTooLarge",
    "ServeEngine",
    "ServeError",
    "SwapRejected",
    "TenantDegraded",
    "TenantQuota",
    "TenantTable",
    "pad_ids",
    "serve_health_record",
]

_LAZY = {
    "BucketLadder": ("dgraph_tpu.serve.bucketing", "BucketLadder"),
    "EngineStopped": ("dgraph_tpu.serve.errors", "EngineStopped"),
    "MicroBatcher": ("dgraph_tpu.serve.batcher", "MicroBatcher"),
    "ModelRegistry": ("dgraph_tpu.serve.registry", "ModelRegistry"),
    "QueueFull": ("dgraph_tpu.serve.errors", "QueueFull"),
    "QuotaExceeded": ("dgraph_tpu.serve.errors", "QuotaExceeded"),
    "RequestTimeout": ("dgraph_tpu.serve.errors", "RequestTimeout"),
    "RequestTooLarge": ("dgraph_tpu.serve.errors", "RequestTooLarge"),
    "ServeEngine": ("dgraph_tpu.serve.engine", "ServeEngine"),
    "ServeError": ("dgraph_tpu.serve.errors", "ServeError"),
    "SwapRejected": ("dgraph_tpu.serve.errors", "SwapRejected"),
    "TenantDegraded": ("dgraph_tpu.serve.errors", "TenantDegraded"),
    "TenantQuota": ("dgraph_tpu.serve.tenancy", "TenantQuota"),
    "TenantTable": ("dgraph_tpu.serve.tenancy", "TenantTable"),
    "pad_ids": ("dgraph_tpu.serve.bucketing", "pad_ids"),
    "serve_health_record": ("dgraph_tpu.serve.health", "serve_health_record"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: pay the import once
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
