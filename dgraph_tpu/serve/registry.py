"""ModelRegistry: named model/graph versions behind one micro-batcher.

The single-engine serving stack bakes ONE (model, checkpoint, graph) triple
into the process for its lifetime; every change — a new checkpoint, a
re-planned graph generation — meant a restart and a cold warmup. The
registry is the control-plane indirection that removes that coupling:

- Named entries, each one warmed :class:`~dgraph_tpu.serve.engine.
  ServeEngine` plus its lineage (the audit trail of checkpoint swaps and
  graph-generation adoptions that produced its current state).
- ONE entry is *active*; the :class:`~dgraph_tpu.serve.batcher.
  MicroBatcher` resolves the active engine **per batch**, so activating a
  replacement engine is an atomic flip between batches — in-flight batches
  complete on the engine they started on, the next batch runs on the new
  one, and no request is ever dropped by an adoption.
- Checkpoint rollover (:meth:`~dgraph_tpu.serve.engine.ServeEngine.
  swap_params`) mutates an entry's engine in place (same executables, new
  params) and appends to its lineage; graph-delta adoption
  (:mod:`~dgraph_tpu.serve.deltas`) builds a NEW engine for the new
  generation and :meth:`~ModelRegistry.activate`\\ s it.

This module is **jax-free by contract** (``analysis.lint``'s
``jax-free-module`` rule): the engines it holds are opaque objects, so the
registry/lineage bookkeeping stays importable by the train supervisor and
health tooling in processes that never dial a backend.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class _Entry:
    __slots__ = ("name", "engine", "registered_at", "lineage", "retired")

    def __init__(self, name: str, engine, lineage: Optional[list] = None):
        self.name = name
        self.engine = engine
        self.registered_at = time.time()
        self.lineage = list(lineage or [])
        self.retired = False


class ModelRegistry:
    """Named serving versions with one atomically-switchable active entry.

    Thread-safe: ``activate`` runs on operator/control threads while the
    batcher's worker thread reads :attr:`active_engine` per batch — the
    flip is one reference assignment under the lock, and readers only ever
    see entirely the old or entirely the new engine.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict = {}
        self._active: Optional[str] = None

    # --- registration / activation ---

    def register(self, name: str, engine, *, activate: bool = False,
                 lineage: Optional[list] = None) -> None:
        """Add (or replace) the named entry. Replacing an entry whose
        engine the batcher may be flushing on is safe — the old engine
        object stays alive until its in-flight batch resolves. Replacing
        the entry that is (or becomes) ACTIVE applies the same
        ladder-coverage rule as :meth:`activate`: requests already
        admitted against the old ladder must still fit."""
        name = str(name)
        with self._lock:
            prior = self._entries.get(name)
            becomes_active = activate or self._active in (None, name)
            if (
                prior is not None and becomes_active
                and not _ladder_covers(engine, prior.engine)
            ):
                raise ValueError(
                    f"replacement engine for active model {name!r} has a "
                    "smaller bucket ladder than the entry it replaces; "
                    "admitted requests could no longer fit"
                )
            entry = _Entry(name, engine,
                           lineage=prior.lineage if prior else lineage)
            self._entries[name] = entry
            if activate or self._active is None:
                self._active = name

    def activate(self, name: str, engine=None,
                 *, note: Optional[dict] = None) -> None:
        """Make ``name`` the active entry (optionally installing a new
        engine for it first — the graph-delta adoption path). The ladder
        of a replacement engine must cover the old one's ``max_size`` so
        requests admitted against the old ladder still fit; a shrinking
        swap must go through a fresh entry name instead."""
        name = str(name)
        with self._lock:
            if engine is not None:
                prior = self._entries.get(name)
                if prior is not None and not _ladder_covers(
                    engine, prior.engine
                ):
                    raise ValueError(
                        f"replacement engine for {name!r} has a smaller "
                        "bucket ladder than the entry it replaces; "
                        "admitted requests could no longer fit"
                    )
                entry = _Entry(name, engine,
                               lineage=prior.lineage if prior else None)
                if note:
                    entry.lineage.append(dict(note))
                self._entries[name] = entry
            if name not in self._entries:
                raise KeyError(f"no registered model {name!r}")
            self._active = name

    def retire(self, name: str) -> None:
        """Drop a named entry (must not be active)."""
        name = str(name)
        with self._lock:
            if name == self._active:
                raise ValueError(f"cannot retire the active model {name!r}")
            self._entries.pop(name, None)

    def note(self, name: str, record: dict) -> None:
        """Append one lineage record (swap/adoption outcome) to an entry."""
        with self._lock:
            entry = self._entries.get(str(name))
            if entry is not None:
                entry.lineage.append(dict(record))

    # --- lookup ---

    @property
    def active_name(self) -> Optional[str]:
        # under the lock like every other reader: _active is flipped by
        # activate()/register() on operator threads, and an unlocked
        # read here was the one hole in the registry's locking story
        # (host-lock-discipline; pinned in test_analysis_host)
        with self._lock:
            return self._active

    @property
    def active_engine(self):
        """The engine the next batch should run on; raises KeyError with
        an empty registry (a misconfigured stack must fail loudly, not
        NoneType its way into the worker thread)."""
        with self._lock:
            if self._active is None:
                raise KeyError("ModelRegistry has no active model")
            return self._entries[self._active].engine

    def get(self, name: str):
        with self._lock:
            entry = self._entries.get(str(name))
            if entry is None:
                raise KeyError(f"no registered model {name!r}")
            return entry.engine

    def names(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def lineage(self, name: str) -> list:
        with self._lock:
            entry = self._entries.get(str(name))
            return list(entry.lineage) if entry else []

    def record(self) -> dict:
        """JSONL-able control-plane snapshot for the serve_health record."""
        with self._lock:
            return {
                "active": self._active,
                "models": {
                    n: {
                        "registered_at": e.registered_at,
                        "lineage": list(e.lineage),
                    }
                    for n, e in sorted(self._entries.items())
                },
            }


def _ladder_covers(new_engine, old_engine) -> bool:
    """True when the new engine's ladder can serve every request size the
    old one admitted (duck-typed: engines are opaque here by the jax-free
    contract)."""
    try:
        return int(new_engine.ladder.max_size) >= int(old_engine.ladder.max_size)
    except AttributeError:
        return True
