"""``python -m dgraph_tpu.serve`` — online GNN inference serving CLI.

Default mode builds a serving stack over a synthetic (or npz) graph, warms
every bucket, runs the requested traffic through the micro-batcher, and
emits a ``serve_health`` JSONL record.

``--selftest`` is the single-process CPU end-to-end check (registered as a
tier-1 test): synthetic graph -> init params -> checkpoint save/restore
round trip -> plan via the on-disk cache -> warmup -> mixed-size traffic
through the micro-batcher -> hard assertions:

- zero XLA compiles after warmup (``recompiles_since_warmup == 0``);
- bucketed served logits == the full eval forward's logits **bit-for-bit**
  (same params, same plan, same ``model_apply`` body);
- an over-ladder request is rejected with the structured ``too_large``
  error;
- **hot-swap rollover** (control plane, compile-free): a second
  checkpoint step swaps in under the same warmed executables — zero new
  compiles, served==eval parity under the NEW params — and a
  chaos-injected fault mid-swap rolls back to the adopted params with the
  structured ``swap_rejected`` error;
- **tenant quotas**: a flooding tenant is shed with the structured
  ``quota`` rejection while a second tenant on the same batcher keeps
  being served.

Exit code 0 only if every assertion holds.
"""

from __future__ import annotations

import dataclasses
import json
import tempfile
from typing import Optional


@dataclasses.dataclass
class Config:
    """Online GNN inference serving (``--selftest`` for the CPU e2e check)."""

    selftest: bool = False
    # graph (synthetic SBM unless data_path points at an npz)
    data_path: Optional[str] = None
    num_nodes: int = 400
    num_classes: int = 4
    feat_dim: int = 16
    avg_degree: float = 6.0
    partition: str = "random"
    world_size: int = 0  # 0 = all devices
    # model
    model: str = "gcn"  # gcn | sage
    hidden: int = 16
    num_layers: int = 2
    seed: int = 0
    # checkpoint / plan cache ("" = fresh params / no cache; selftest uses a
    # tempdir for both so the restore + cache paths are always exercised)
    ckpt_dir: str = ""
    plan_cache: str = ""
    # bucket ladder; use_tuned_ladder lets an adopted TuningRecord's
    # serve geometry (dgraph_tpu.tune) override these three flags
    min_bucket: int = 8
    max_bucket: int = 64
    growth: float = 2.0
    use_tuned_ladder: bool = True
    # micro-batcher
    max_batch_size: int = 8
    max_delay_ms: float = 2.0
    max_queue_depth: int = 64
    request_timeout_s: float = 30.0
    # traffic
    requests: int = 32
    log_path: str = "logs/serve.jsonl"


def build_serving(cfg: Config, tenants=None):
    """Graph -> params (checkpoint round trip if configured) -> warmed
    engine + batcher. Shared by this CLI and experiments/serve_bench.py
    (which passes its ``TenantTable`` as ``tenants`` for the multi-tenant
    open-loop mode)."""
    import jax
    import numpy as np

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN, GraphSAGE
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.bucketing import BucketLadder
    from dgraph_tpu.serve.engine import ServeEngine
    from dgraph_tpu.train.checkpoint import save_checkpoint
    from dgraph_tpu.train.loop import init_params

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    comm = Communicator.init_process_group("tpu", world_size=world)

    if cfg.data_path:
        z = np.load(cfg.data_path)
        masks = {
            k.removesuffix("_mask"): z[k] for k in z.files if k.endswith("_mask")
        }
        # OGB exports say "valid"; the split vocabulary here is "val" — the
        # same rename experiments/ogb_gcn.py applies (keep in sync: a
        # missed rename silently serves/evaluates on ALL vertices)
        if "valid" in masks and "val" not in masks:
            masks["val"] = masks.pop("valid")
        data = {
            "edge_index": z["edge_index"],
            "features": z["features"],
            "labels": z["labels"],
            "masks": masks,
            "num_classes": int(np.asarray(z["labels"]).max()) + 1,
        }
    else:
        data = synthetic.sbm_classification_graph(
            num_nodes=cfg.num_nodes,
            num_classes=cfg.num_classes,
            feat_dim=cfg.feat_dim,
            avg_degree=cfg.avg_degree,
            seed=cfg.seed,
        )
    g = DistributedGraph.from_global(
        data["edge_index"],
        data["features"],
        data["labels"],
        data["masks"],
        world_size=world,
        partition_method=cfg.partition,
        add_symmetric_norm=cfg.model == "gcn",
        plan_cache_dir=cfg.plan_cache,
    )

    C = data["num_classes"]
    if cfg.model == "gcn":
        model = GCN(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    elif cfg.model == "sage":
        model = GraphSAGE(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    else:
        raise SystemExit(f"unknown model {cfg.model}")

    import jax.numpy as jnp

    plan = jax.tree.map(jnp.asarray, g.plan)
    batch = jax.tree.map(jnp.asarray, dict(g.batch("train"), y=g.labels))
    params = init_params(model, mesh, plan, batch, seed=cfg.seed)

    registry = Metrics()
    min_b, max_b, growth = cfg.min_bucket, cfg.max_bucket, cfg.growth
    rec = g.tuning_record
    if cfg.use_tuned_ladder and rec is not None and rec.config.get("serve"):
        s = rec.config["serve"]
        min_b, max_b, growth = s["min_bucket"], s["max_bucket"], s["growth"]
        print(
            f"bucket ladder from tuning record {rec.record_id}: "
            f"min={min_b} max={max_b} growth={growth}"
        )
    ladder = BucketLadder.geometric(min_b, max_b, growth)
    if cfg.ckpt_dir:
        # serving restores from disk, never from in-process state. An EMPTY
        # dir is seeded with the just-initialized params so the save ->
        # restore round trip is exercised (the selftest path); a dir that
        # already holds checkpoints is a REAL training artifact — never
        # write into it, just serve its newest readable step.
        from dgraph_tpu.train.checkpoint import latest_step

        if latest_step(cfg.ckpt_dir) is None:
            save_checkpoint(cfg.ckpt_dir, {"params": params, "step": 0}, 0)
        engine = ServeEngine.from_checkpoint(
            model, mesh, g, cfg.ckpt_dir, ladder=ladder, registry=registry,
        )
    else:
        engine = ServeEngine.from_distributed_graph(
            model, mesh, g, params, ladder=ladder, registry=registry,
        )
    batcher = MicroBatcher(
        engine,
        max_batch_size=cfg.max_batch_size,
        max_delay_ms=cfg.max_delay_ms,
        max_queue_depth=cfg.max_queue_depth,
        default_timeout_s=cfg.request_timeout_s,
        registry=registry,
        tenants=tenants,
    )
    return engine, batcher, g


def _selftest_swap(cfg: Config, engine, log) -> list:
    """Hot-swap rollover under the warmed executables: adopt a perturbed
    step-1 checkpoint (zero compiles, parity pinned), then prove the
    chaos-injected mid-swap fault rolls back to the adopted params."""
    import numpy as np

    from dgraph_tpu import chaos
    from dgraph_tpu.serve.errors import SwapRejected
    from dgraph_tpu.train.checkpoint import restore_checkpoint, save_checkpoint

    failures = []
    state = restore_checkpoint(cfg.ckpt_dir)
    scaled = _scale_float_leaves(state["params"], 1.0625)
    save_checkpoint(cfg.ckpt_dir, {"params": scaled, "step": 1}, 1)
    rec = engine.swap_params(cfg.ckpt_dir, step=1)
    log.write(rec)
    if not rec.get("adopted"):
        failures.append(f"hot swap not adopted: {rec}")
    if engine.recompiles_since_warmup() != 0:
        failures.append("hot swap minted XLA compiles")
    full = engine.full_logits()
    ids = np.arange(min(engine.ladder.sizes[0], engine.num_nodes))
    out = engine.infer(ids)
    r, s = engine.rank_slot(ids)
    if not np.array_equal(out, full[r, s]):
        failures.append("post-swap served logits diverge from eval forward")
    # fault mid-swap: rollback to the adopted (step-1) params, serving
    # uninterrupted — the bits prove nothing moved
    chaos.arm("serve.swap=raise@0")
    try:
        engine.swap_params(cfg.ckpt_dir, step=0)
        failures.append("chaos-injected swap was adopted, not rolled back")
    except SwapRejected as e:
        log.write(e.record())
        if not e.context.get("rolled_back"):
            failures.append("chaos-injected swap rejection not rolled back")
    finally:
        chaos.reset()
    if not np.array_equal(engine.infer(ids), full[r, s]):
        failures.append("rollback disturbed the serving params")
    if engine.recompiles_since_warmup() != 0:
        failures.append("swap rollback minted XLA compiles")
    return failures


def _scale_float_leaves(tree, factor: float):
    import numpy as np

    if isinstance(tree, dict):
        return {k: _scale_float_leaves(v, factor) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(_scale_float_leaves(v, factor) for v in tree)
    arr = np.asarray(tree)
    # exact power-of-two-ish factor keeps the perturbation bit-stable
    return arr * np.asarray(factor, arr.dtype) if arr.dtype.kind == "f" else arr


def _selftest_quota(engine, log) -> list:
    """Per-tenant quotas on a second batcher over the SAME warmed engine
    (compile-free): the flooding tenant is shed with the structured
    ``quota`` error, the calm tenant keeps being served."""
    import numpy as np

    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.errors import QuotaExceeded
    from dgraph_tpu.serve.tenancy import TenantQuota, TenantTable

    failures = []
    table = TenantTable(
        TenantQuota(rps=0.0, burst=8, max_queue_share=1.0),
        quotas={"flood": TenantQuota(rps=0.001, burst=2, max_queue_share=0.25)},
    )
    from dgraph_tpu.obs.metrics import Metrics

    # own metrics registry: the main selftest pins the traffic loop's
    # request count, and the quota probe must not inflate it
    bat = MicroBatcher(
        engine, max_batch_size=4, max_delay_ms=0.5, max_queue_depth=16,
        tenants=table, registry=Metrics(),
    )
    try:
        shed = 0
        for _ in range(6):  # burst of 2, then the bucket is dry
            try:
                bat.infer(np.arange(4), tenant="flood")
            except QuotaExceeded as e:
                shed += 1
                log.write(e.record())
        if shed != 4:
            failures.append(f"flood tenant shed {shed}/4 over-quota requests")
        out = bat.infer(np.arange(4), tenant="calm")
        if out.shape[0] != 4:
            failures.append("calm tenant was not served during the flood")
        snap = table.snapshot()
        if snap["flood"]["shed_quota"] != 4 or snap["calm"]["shed_quota"] != 0:
            failures.append(f"tenant shed accounting wrong: {snap}")
    finally:
        bat.stop()
    if engine.recompiles_since_warmup() != 0:
        failures.append("quota path minted XLA compiles")
    return failures


def main(cfg: Config) -> dict:
    import numpy as np

    from dgraph_tpu.obs.health import startup_record
    from dgraph_tpu.serve.errors import RequestTooLarge
    from dgraph_tpu.serve.health import serve_health_record
    from dgraph_tpu.utils import ExperimentLog

    log = ExperimentLog(cfg.log_path, echo=False)
    log.write(startup_record("serve.cli"))

    tmp = None
    if cfg.selftest and not cfg.ckpt_dir:
        tmp = tempfile.TemporaryDirectory(prefix="dgraph_serve_selftest_")
        cfg.ckpt_dir = tmp.name + "/ckpt"
        cfg.plan_cache = tmp.name + "/plans"
    try:
        engine, batcher, g = build_serving(cfg)
        log.write(engine.warmup())

        rng = np.random.default_rng(cfg.seed)
        failures = []

        # mixed-size closed-loop traffic through the batcher (request sizes
        # clamped to the graph: a tiny --num_nodes under a tall ladder must
        # not crash the sampler)
        expected = engine.full_logits() if cfg.selftest else None
        max_req = min(engine.ladder.max_size, engine.num_nodes)
        for _ in range(cfg.requests):
            n = int(rng.integers(1, max_req + 1))
            ids = rng.choice(engine.num_nodes, size=n, replace=False)
            out = batcher.infer(ids)
            if expected is not None:
                r, s = engine.rank_slot(ids)
                ref = expected[r, s]
                if not np.array_equal(out, ref):
                    failures.append(
                        f"served logits diverge from the eval forward "
                        f"(max abs diff {np.abs(out - ref).max()})"
                    )
                    break
        batcher.stop()

        if cfg.selftest:
            recompiles = engine.recompiles_since_warmup()
            if recompiles != 0:
                failures.append(
                    f"{recompiles} XLA compiles on the hot path after warmup"
                )
            try:
                engine.infer(np.zeros(engine.ladder.max_size + 1, np.int64))
                failures.append("over-ladder request was not rejected")
            except RequestTooLarge as e:
                log.write(e.record())
            failures += _selftest_swap(cfg, engine, log)
            failures += _selftest_quota(engine, log)

        rec = serve_health_record(engine, batcher)
        if failures:
            rec["error"] = "; ".join(failures)
            rec["wedge"] = "stage_failure"
        log.write(rec)
        print(json.dumps(rec, default=str))
        if failures:
            raise SystemExit("selftest FAILED: " + "; ".join(failures))
        return rec
    finally:
        if tmp is not None:
            tmp.cleanup()


if __name__ == "__main__":
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
