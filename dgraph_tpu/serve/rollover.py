"""Hot-swap checkpoint rollover: restore → stage → validate → adopt|rollback.

A serving fleet cannot restart to pick up a new checkpoint: the bucket
ladder's AOT warmup is seconds of XLA compiles, and a restart drops every
queued request. Params, however, are *arguments* to the warmed executables
— so a rollover that keeps structure/shape/dtype identical replays the
exact same compiled programs with new weights, and "install the new
checkpoint" reduces to one reference assignment. This module is the state
machine around that assignment:

```
            restore_checkpoint(step|path)            jnp.asarray
  RESTORE ────────────────────────────────► STAGED ─────────────► VALIDATE
                                                                     │
          structure/shape/dtype == warmed executables?  ── no ──► ROLLBACK
          every param leaf finite (non-finite guard)?   ── no ──► ROLLBACK
          served == eval parity, bit-for-bit,                        │
            through the CACHED executables?             ── no ──► ROLLBACK
          zero new jit-cache entries?                   ── no ──► ROLLBACK
                          │ yes
                          ▼
                        ADOPT   (engine._params = staged, under the lock)
```

Every oracle runs with the *staged* tree passed as an argument — the live
pointer has not moved yet — so ROLLBACK is free: the prior params were
never unplugged, in-flight and queued requests never notice, and the
structured :class:`~dgraph_tpu.serve.errors.SwapRejected` carries the full
validation record. ADOPT is atomic per batch: ``infer`` reads
``engine._params`` once per dispatch, so a batch sees entirely old or
entirely new params, never a mix. The ``serve.swap`` chaos point fires
between staging and validation — an injected fault there proves the
rollback path sheds nothing (pinned by tests/test_serve_control.py and the
serve CLI selftest).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from dgraph_tpu.serve.errors import SwapRejected


def place_like(new, old):
    """Device-place ``new`` to mirror ``old``'s placement: a
    multi-device-sharded leaf is reproduced exactly (a layout change would
    specialize a fresh executable — the recompile the swap/append paths
    exist to avoid), while a single-device leaf stays UNCOMMITTED like the
    engine's construction path made it — committing it would conflict with
    mesh-sharded co-arguments inside jit. Shared by the rollover staging
    below and ``ServeEngine.append_vertices`` so the two paths cannot
    drift."""
    arr = jnp.asarray(new)
    sharding = getattr(old, "sharding", None)
    if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
        arr = jax.device_put(arr, sharding)
    return arr


def params_mismatch(old, new) -> Optional[str]:
    """None when ``new`` can replay ``old``'s executables (same treedef,
    leaf shapes and dtypes); otherwise a human-readable reason. Anything
    non-None would force an XLA recompile on adoption — the one cost a
    hot swap exists to avoid — so it rejects instead."""
    old_leaves, old_def = jax.tree.flatten(old)
    new_leaves, new_def = jax.tree.flatten(new)
    if old_def != new_def:
        return f"param tree structure differs: {old_def} != {new_def}"
    for i, (a, b) in enumerate(zip(old_leaves, new_leaves)):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            return (
                f"param leaf {i} differs: {a.shape}/{a.dtype} vs "
                f"{b.shape}/{b.dtype}"
            )
    return None


def nonfinite_param_leaves(params) -> int:
    """Count of param leaves carrying any non-finite value — the rollover
    analog of the training-side non-finite step guard
    (:mod:`dgraph_tpu.train.guard`): a checkpoint that diverged before it
    was saved must never reach traffic."""
    bad = 0
    for leaf in jax.tree.leaves(params):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad += 1
    return bad


def _restore(engine, source, step):
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    ckpt_dir = source if source is not None else engine.ckpt_dir
    if not ckpt_dir:
        raise SwapRejected(
            "no checkpoint source: pass a directory (or params=) or build "
            "the engine via from_checkpoint",
            reason="no_source", rolled_back=False,
        )
    try:
        state = restore_checkpoint(ckpt_dir, step=step)
    except Exception as e:  # noqa: BLE001 — unreadable/corrupt checkpoint
        raise SwapRejected(
            f"checkpoint restore failed: {type(e).__name__}: {e}",
            reason="restore_failed", ckpt_dir=ckpt_dir, rolled_back=False,
        )
    if state is None:
        raise SwapRejected(
            f"no checkpoint under {ckpt_dir!r}",
            reason="not_found", ckpt_dir=ckpt_dir, rolled_back=False,
        )
    params = (
        state["params"]
        if isinstance(state, dict) and "params" in state
        else state
    )
    restored_step = (
        int(state["step"])
        if isinstance(state, dict) and "step" in state
        else step
    )
    return params, ckpt_dir, restored_step


def swap_params(engine, source=None, *, step: Optional[int] = None,
                params=None, parity_ids=None) -> dict:
    """Run the full rollover state machine on ``engine``; returns the
    adopted lineage record or raises :class:`SwapRejected` with the
    rollback record (prior params still serving either way but rejection).

    ``parity_ids``: explicit vertex ids for the served==eval oracle;
    default is the first ``min(smallest bucket, num_nodes)`` real ids.
    """
    from dgraph_tpu import chaos

    t0 = time.perf_counter()
    rec = {
        "kind": "serve_rollover",
        "event": "swap",
        "adopted": False,
        "rolled_back": False,
    }

    def _reject(reason: str, detail: str, **ctx):
        rec.update(reason=reason, detail=detail, rolled_back=True,
                   swap_s=round(time.perf_counter() - t0, 3), **ctx)
        engine.lineage.append(dict(rec))
        engine.registry.counter("serve.swap_rejected")
        raise SwapRejected(
            f"checkpoint swap rolled back ({reason}): {detail}; prior "
            "params remain installed",
            **{k: v for k, v in rec.items() if k != "kind"},
        )

    # RESTORE (outside the engine lock: disk IO must not stall the worker)
    if params is None:
        try:
            params, ckpt_dir, restored_step = _restore(engine, source, step)
        except SwapRejected as e:
            # restore-phase rejections land in the lineage too — the
            # contract is ONE record per attempt, adopted or not
            rec.update(
                rolled_back=True,
                reason=e.context.get("reason", "restore"),
                detail=str(e),
                ckpt_dir=e.context.get("ckpt_dir", source),
                step=step,
                swap_s=round(time.perf_counter() - t0, 3),
            )
            engine.lineage.append(dict(rec))
            engine.registry.counter("serve.swap_rejected")
            raise
        rec.update(ckpt_dir=ckpt_dir, step=restored_step)
    else:
        rec.update(ckpt_dir=None, step=step)

    try:
        # the chaos boundary: a fault injected here (serve.swap=raise@0)
        # exercises the mid-swap rollback path deterministically
        chaos.fire("serve.swap")

        # VALIDATE structure against the warmed executables
        mismatch = params_mismatch(engine._params, params)
        if mismatch:
            _reject("structure_mismatch", mismatch)

        # non-finite guard (host-side; the checkpoint may be freshly
        # restored numpy — no device work yet)
        bad = nonfinite_param_leaves(params)
        if bad:
            _reject(
                "nonfinite_params",
                f"{bad} param leaf(s) carry non-finite values",
            )

        # STAGE on device, leaf-by-leaf onto the LIVE params' shardings:
        # a checkpoint restored on a different layout (host numpy, a
        # single-device orbax restore, a different mesh at save time)
        # must land exactly where the warmed executables expect their
        # params operand, or validation would specialize a fresh
        # executable — the recompile the swap exists to avoid. Every
        # oracle below passes `staged` as an ARGUMENT through the cached
        # executables; the live pointer has not moved
        staged = jax.tree.map(place_like, params, engine._params)
        compiles_before = engine._total_compiles()

        # served == eval parity oracle: the full eval-forward of the NEW
        # checkpoint vs the bucketed+gathered serving path, bit-for-bit
        with jax.set_mesh(engine.mesh):
            full = np.asarray(jax.block_until_ready(
                engine._full(staged, engine._batch, engine._plan)
            ))
        if not np.isfinite(
            full[engine._id_rank, engine._id_slot]
        ).all():
            _reject(
                "nonfinite_logits",
                "new checkpoint produces non-finite logits on real "
                "vertices",
            )
        bucket = engine.ladder.sizes[0]
        if parity_ids is None:
            parity_ids = np.arange(
                min(int(bucket), engine.num_nodes), dtype=np.int64
            )
        ids = np.asarray(parity_ids)
        from dgraph_tpu.serve.bucketing import pad_ids

        padded, n = pad_ids(ids, engine.ladder.bucket_for(ids.shape[0]))
        rank_idx = jnp.asarray(engine._id_rank[padded])
        slot_idx = jnp.asarray(engine._id_slot[padded])
        with jax.set_mesh(engine.mesh):
            served = np.asarray(jax.block_until_ready(
                engine._forwards[engine.ladder.bucket_for(ids.shape[0])](
                    staged, engine._batch, engine._plan, rank_idx, slot_idx
                )
            ))[:n]
        ref = full[engine._id_rank[ids], engine._id_slot[ids]]
        if not np.array_equal(served, ref):
            _reject(
                "parity",
                "served logits diverge from the eval forward under the "
                f"new checkpoint (max abs diff "
                f"{float(np.abs(served - ref).max())})",
            )

        # jit-cache pin: adoption must not have minted executables
        new_compiles = engine._total_compiles() - compiles_before
        if new_compiles:
            _reject(
                "recompile",
                f"{new_compiles} new jit-cache entries during validation "
                "(the staged tree does not replay the warmed executables)",
            )
    except SwapRejected:
        raise
    except Exception as e:  # noqa: BLE001 — fault mid-swap: roll back
        _reject("fault", f"{type(e).__name__}: {e}")

    # ADOPT: one reference assignment under the engine lock — per-batch
    # atomic (infer reads engine._params once per dispatch)
    with engine._lock:
        engine._params = staged
    rec.update(adopted=True, swap_s=round(time.perf_counter() - t0, 3))
    engine.lineage.append(dict(rec))
    engine.registry.counter("serve.swaps_adopted")
    engine.registry.gauge("serve.swap_s", rec["swap_s"])
    return rec
