"""Micro-batching request queue: bounded depth, deadline, flush policy.

Single-request inference wastes the one resource a TPU serving process has
plenty of — bucket capacity: a forward over the partitioned graph costs the
same whether it gathers 3 target rows or 300. :class:`MicroBatcher` closes
that gap by coalescing concurrent requests into one padded engine call,
with the three safety properties an online queue needs:

- **bounded depth** — ``submit`` raises :class:`~dgraph_tpu.serve.errors.
  QueueFull` (a structured rejection) once ``max_queue_depth`` requests
  wait; overload becomes fast client-visible backpressure instead of
  unbounded latency.
- **bounded delay** — a batch flushes when ``max_batch_size`` requests are
  waiting, when the *oldest* waiting request has aged ``max_delay_ms``, or
  when the next request would overflow the largest shape bucket.
- **deadlines** — a request that ages past its timeout while queued is
  rejected with :class:`~dgraph_tpu.serve.errors.RequestTimeout` and never
  runs (its client already gave up; spending a batch slot on it only adds
  latency for live requests). An expired-only batch flushes empty: no
  engine call at all.

One worker thread owns the engine (device work stays single-threaded, the
same assumption the training driver makes); clients get a
``concurrent.futures.Future`` resolving to the logits slice or the
structured error.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from dgraph_tpu.obs.metrics import Metrics
from dgraph_tpu.serve.errors import (
    EngineStopped,
    QueueFull,
    RequestTimeout,
    RequestTooLarge,
)


@dataclasses.dataclass
class _Pending:
    ids: np.ndarray
    future: Future
    enqueued_at: float  # time.monotonic()
    deadline: float


class MicroBatcher:
    """Groups concurrent requests into one padded :class:`~dgraph_tpu.serve.
    engine.ServeEngine` call. See the module docstring for the flush and
    rejection semantics."""

    def __init__(
        self,
        engine,
        *,
        max_batch_size: int = 8,
        max_delay_ms: float = 2.0,
        max_queue_depth: int = 64,
        default_timeout_s: float = 30.0,
        registry: Optional[Metrics] = None,
    ):
        if max_batch_size < 1 or max_queue_depth < 1:
            raise ValueError("max_batch_size and max_queue_depth must be >= 1")
        self.engine = engine
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue_depth = int(max_queue_depth)
        self.default_timeout_s = float(default_timeout_s)
        self.registry = registry if registry is not None else engine.registry
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._stopped = False
        self._worker = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._worker.start()
        # interpreter exit kills daemon threads WITHOUT joining; a worker
        # torn down mid-XLA-dispatch aborts the whole process ("terminate
        # called without an active exception"), so always stop cleanly
        import atexit

        atexit.register(self.stop)

    def __len__(self) -> int:
        """Current queue depth (requests waiting, not in flight)."""
        with self._cv:
            return len(self._q)

    # --- client side ---

    def submit(self, node_ids, timeout_s: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future of the [n, C] logits.

        Raises (never queues past) :class:`QueueFull` at capacity,
        :class:`RequestTooLarge` for requests no bucket fits, and
        :class:`EngineStopped` after :meth:`stop`.
        """
        ids = np.asarray(node_ids)
        if ids.ndim != 1:
            raise ValueError(f"node_ids must be 1-D, got shape {ids.shape}")
        # full request validation up front: an impossible request must not
        # occupy a queue slot, and — because the worker CONCATENATES
        # requests — must never reach the engine, where its failure would
        # fan out to every innocent request coalesced into the same batch
        try:
            self.engine.ladder.bucket_for(ids.shape[0])
        except RequestTooLarge:
            self.registry.counter("serve.rejected_too_large")
            raise
        num_nodes = getattr(self.engine, "num_nodes", None)
        if num_nodes is not None and ids.size and (
            ids.min() < 0 or ids.max() >= num_nodes
        ):
            raise ValueError(
                f"node ids must be in [0, {num_nodes}), got "
                f"[{ids.min()}, {ids.max()}]"
            )
        now = time.monotonic()
        timeout_s = self.default_timeout_s if timeout_s is None else float(timeout_s)
        with self._cv:
            if self._stopped:
                raise EngineStopped("batcher is stopped")
            if len(self._q) >= self.max_queue_depth:
                self.registry.counter("serve.rejected_backpressure")
                raise QueueFull(
                    f"queue at capacity ({self.max_queue_depth} requests "
                    "waiting); retry with backoff",
                    queue_depth=len(self._q),
                    max_queue_depth=self.max_queue_depth,
                )
            fut: Future = Future()
            self._q.append(_Pending(ids, fut, now, now + timeout_s))
            self.registry.gauge("serve.queue_depth", float(len(self._q)))
            self._cv.notify()
        return fut

    def infer(self, node_ids, timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking submit: logits [n, C], or raises the structured error."""
        return self.submit(node_ids, timeout_s).result()

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Stop the worker (drains whatever is queued, rejecting anything
        still unserved at join timeout with :class:`EngineStopped`).
        Idempotent; also runs via atexit if the owner forgot."""
        import atexit

        atexit.unregister(self.stop)
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._worker.join(timeout=join_timeout_s)
        with self._cv:
            while self._q:
                p = self._q.popleft()
                if not p.future.done():
                    p.future.set_exception(EngineStopped("batcher stopped"))

    # --- worker side ---

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._flush(batch)

    def _collect(self):
        """Block until a batch is ready per the flush policy; None = exit."""
        with self._cv:
            while not self._q:
                if self._stopped:
                    return None
                self._cv.wait(0.1)
            # batch window: fill up to max_batch_size or until the OLDEST
            # request has waited max_delay_ms (per-batch added latency is
            # bounded by the delay knob, not by arrival luck)
            flush_at = self._q[0].enqueued_at + self.max_delay_ms / 1e3
            while len(self._q) < self.max_batch_size and not self._stopped:
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            batch, total = [], 0
            cap = self.engine.ladder.max_size
            while self._q and len(batch) < self.max_batch_size:
                nxt = self._q[0]
                if batch and total + nxt.ids.shape[0] > cap:
                    break  # would overflow the largest bucket; next batch
                batch.append(self._q.popleft())
                total += nxt.ids.shape[0]
            self.registry.gauge("serve.queue_depth", float(len(self._q)))
            return batch

    def _flush(self, batch) -> None:
        now = time.monotonic()
        live = []
        for p in batch:
            if now > p.deadline:
                self.registry.counter("serve.rejected_timeout")
                p.future.set_exception(
                    RequestTimeout(
                        f"request expired after {now - p.enqueued_at:.3f}s in "
                        "queue (timeout "
                        f"{p.deadline - p.enqueued_at:.3f}s)",
                        waited_s=round(now - p.enqueued_at, 4),
                    )
                )
            else:
                live.append(p)
        if not live:
            return  # expired-only batch: flush empty, no engine call
        ids = np.concatenate([p.ids for p in live]) if len(live) > 1 else live[0].ids
        try:
            out = self.engine.infer(ids)
        except Exception as e:  # noqa: BLE001 — fan the failure to every waiter
            for p in live:
                p.future.set_exception(e)
            return
        off = 0
        done = time.monotonic()
        for p in live:
            n = p.ids.shape[0]
            p.future.set_result(out[off : off + n])
            off += n
            self.registry.histogram(
                "serve.request_ms", (done - p.enqueued_at) * 1e3
            )
        self.registry.counter("serve.batches")
        self.registry.histogram("serve.requests_per_batch", float(len(live)))
