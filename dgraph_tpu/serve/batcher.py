"""Micro-batching request queue: bounded depth, deadline, flush policy.

Single-request inference wastes the one resource a TPU serving process has
plenty of — bucket capacity: a forward over the partitioned graph costs the
same whether it gathers 3 target rows or 300. :class:`MicroBatcher` closes
that gap by coalescing concurrent requests into one padded engine call,
with the three safety properties an online queue needs:

- **bounded depth** — ``submit`` raises :class:`~dgraph_tpu.serve.errors.
  QueueFull` (a structured rejection) once ``max_queue_depth`` requests
  wait; overload becomes fast client-visible backpressure instead of
  unbounded latency.
- **bounded delay** — a batch flushes when ``max_batch_size`` requests are
  waiting, when the *oldest* waiting request has aged ``max_delay_ms``, or
  when the next request would overflow the largest shape bucket.
- **deadlines** — a request that ages past its timeout while queued is
  rejected with :class:`~dgraph_tpu.serve.errors.RequestTimeout` and never
  runs (its client already gave up; spending a batch slot on it only adds
  latency for live requests). An expired-only batch flushes empty: no
  engine call at all.

One worker thread owns the engine (device work stays single-threaded, the
same assumption the training driver makes); clients get a
``concurrent.futures.Future`` resolving to the logits slice or the
structured error.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from dgraph_tpu.obs import spans
from dgraph_tpu.obs.metrics import Metrics
from dgraph_tpu.serve.errors import (
    EngineStopped,
    QueueFull,
    RequestTimeout,
    RequestTooLarge,
    WorkerCrashed,
)
from dgraph_tpu.serve.tenancy import TenantTable


@dataclasses.dataclass
class _Pending:
    ids: np.ndarray
    future: Future
    enqueued_at: float  # time.monotonic()
    deadline: float
    # the request's span (obs.spans; the shared no-op when tracing is
    # off), started at submit on the client thread and ended wherever the
    # request resolves — worker flush, rejection, crash, or stop. One span
    # covers the whole enqueue -> batch-form -> pad -> infer -> reply
    # lifecycle, so the trace id survives every rejection path.
    span: object = spans.NOOP_SPAN
    popped_at: float = 0.0  # when the worker pulled it off the queue
    # tenant id this request was admitted under (None = no tenant table
    # configured); every resolution path pairs the admit with one release
    tenant: Optional[str] = None


class MicroBatcher:
    """Groups concurrent requests into one padded :class:`~dgraph_tpu.serve.
    engine.ServeEngine` call. See the module docstring for the flush and
    rejection semantics."""

    def __init__(
        self,
        engine,
        *,
        max_batch_size: int = 8,
        max_delay_ms: float = 2.0,
        max_queue_depth: int = 64,
        default_timeout_s: float = 30.0,
        registry: Optional[Metrics] = None,
        tenants: Optional[TenantTable] = None,
    ):
        if max_batch_size < 1 or max_queue_depth < 1:
            raise ValueError("max_batch_size and max_queue_depth must be >= 1")
        # `engine` may be a bare ServeEngine OR a ModelRegistry
        # (dgraph_tpu.serve.registry): with a registry the ACTIVE engine is
        # resolved per batch, which is what makes checkpoint/graph
        # adoption an atomic between-batches flip with zero dropped
        # requests
        self._source = engine
        # per-tenant admission (token-bucket quotas, queue shares,
        # per-tenant degraded shedding); None = single-tenant behavior,
        # byte-for-byte the pre-tenancy semantics
        self.tenants = tenants
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue_depth = int(max_queue_depth)
        self.default_timeout_s = float(default_timeout_s)
        self.registry = registry if registry is not None else self.engine.registry
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._stopped = False
        # requests popped from the queue but not yet resolved — reachable
        # by the crash handler so a worker dying mid-batch can still fail
        # them (they would otherwise hang until client timeout)
        self._inflight: list = []
        self._worker = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True
        )
        self._worker.start()
        # interpreter exit kills daemon threads WITHOUT joining; a worker
        # torn down mid-XLA-dispatch aborts the whole process ("terminate
        # called without an active exception"), so always stop cleanly
        import atexit

        atexit.register(self.stop)

    @property
    def engine(self):
        """The engine the next operation should run on — the bare engine,
        or the registry's ACTIVE entry (read per call, so a control-plane
        ``activate`` flips new batches to a new engine atomically)."""
        src = self._source
        return src.active_engine if hasattr(src, "active_engine") else src

    def __len__(self) -> int:
        """Current queue depth (requests waiting, not in flight)."""
        with self._cv:
            return len(self._q)

    # --- client side ---

    def submit(self, node_ids, timeout_s: Optional[float] = None,
               *, tenant: Optional[str] = None) -> Future:
        """Enqueue one request; returns a Future of the [n, C] logits.

        Raises (never queues past) :class:`QueueFull` at capacity,
        :class:`RequestTooLarge` for requests no bucket fits,
        :class:`EngineStopped` after :meth:`stop`, and — with a
        :class:`~dgraph_tpu.serve.tenancy.TenantTable` configured — the
        structured per-tenant rejections (:class:`~dgraph_tpu.serve.
        errors.QuotaExceeded` / :class:`~dgraph_tpu.serve.errors.
        TenantDegraded`) for ``tenant``'s own overage, leaving every other
        tenant's admission untouched.
        """
        from dgraph_tpu.serve.tenancy import DEFAULT_TENANT

        ids = np.asarray(node_ids)
        if ids.ndim != 1:
            raise ValueError(f"node_ids must be 1-D, got shape {ids.shape}")
        # ONE tenant-id resolution shared by every accounting path below
        # (admit, failure attribution): '' and None must not land in
        # different tenant buckets
        tenant_id = DEFAULT_TENANT if tenant is None else str(tenant)
        # the per-request span opens at submit (client thread) and follows
        # the request across the worker thread; rejection paths end it
        # with the structured error code, so the trace id survives
        # QueueFull/too-large/stopped exactly like a served request
        req_span = spans.span("serve.request", n=int(ids.shape[0]),
                              tenant=tenant_id if self.tenants else tenant)
        # full request validation up front: an impossible request must not
        # occupy a queue slot, and — because the worker CONCATENATES
        # requests — must never reach the engine, where its failure would
        # fan out to every innocent request coalesced into the same batch.
        # A malformed request is also a TENANT signal: poisoned payloads
        # count toward that tenant's (and only that tenant's) degrading.
        try:
            self.engine.ladder.bucket_for(ids.shape[0])
        except RequestTooLarge:
            self.registry.counter("serve.rejected_too_large")
            self._note_tenant_failure(tenant_id)
            req_span.end(error="too_large")
            raise
        num_nodes = getattr(self.engine, "num_nodes", None)
        if num_nodes is not None and ids.size and (
            ids.min() < 0 or ids.max() >= num_nodes
        ):
            self._note_tenant_failure(tenant_id)
            req_span.end(error="bad_ids")
            raise ValueError(
                f"node ids must be in [0, {num_nodes}), got "
                f"[{ids.min()}, {ids.max()}]"
            )
        now = time.monotonic()
        timeout_s = self.default_timeout_s if timeout_s is None else float(timeout_s)
        with self._cv:
            if self._stopped:
                req_span.end(error="stopped")
                raise EngineStopped("batcher is stopped")
            if len(self._q) >= self.max_queue_depth:
                self.registry.counter("serve.rejected_backpressure")
                req_span.end(error="backpressure")
                raise QueueFull(
                    f"queue at capacity ({self.max_queue_depth} requests "
                    "waiting); retry with backoff",
                    queue_depth=len(self._q),
                    max_queue_depth=self.max_queue_depth,
                )
            admitted_tenant = None
            if self.tenants is not None:
                # per-tenant admission (rate bucket, queue share,
                # degraded shedding) — raises the structured rejection;
                # success charges a queue slot that every resolution
                # path below releases exactly once
                try:
                    admitted_tenant = self.tenants.admit(
                        tenant_id, self.max_queue_depth
                    )
                except Exception as e:
                    code = getattr(e, "code", "quota")
                    self.registry.counter(f"serve.rejected_{code}")
                    req_span.end(error=code)
                    raise
            fut: Future = Future()
            self._q.append(
                _Pending(ids, fut, now, now + timeout_s, span=req_span,
                         tenant=admitted_tenant)
            )
            self.registry.gauge("serve.queue_depth", float(len(self._q)))
            self._cv.notify()
        return fut

    def infer(self, node_ids, timeout_s: Optional[float] = None,
              *, tenant: Optional[str] = None) -> np.ndarray:
        """Blocking submit: logits [n, C], or raises the structured error."""
        return self.submit(node_ids, timeout_s, tenant=tenant).result()

    def _note_tenant_failure(self, tenant_id: str) -> None:
        """One request-level failure attributed to ``tenant_id`` (and the
        shared degraded counter when that failure tips the tenant over) —
        the ONE place both the submit-validation and worker paths report
        through, so the two cannot count differently."""
        if self.tenants is not None and self.tenants.observe_failure(
            tenant_id
        ):
            self.registry.counter("serve.tenant_degraded")

    def _release_tenant(self, p: _Pending, success: Optional[bool] = None
                        ) -> None:
        """Pair one admitted request with its queue-slot release (+ the
        success/failure signal feeding per-tenant degrading)."""
        if self.tenants is None or p.tenant is None:
            return
        self.tenants.release(p.tenant)
        if success is True:
            self.tenants.observe_success(p.tenant)
        elif success is False:
            self._note_tenant_failure(p.tenant)

    @staticmethod
    def _fail_future(fut: Future, err: Exception) -> None:
        """Resolve a future with ``err`` unless the client already did
        (done/cancelled). A bare done() pre-check is NOT enough: a client
        can cancel() between the check and set_exception(), and the
        InvalidStateError would abort whichever cleanup loop was running —
        leaving the remaining futures hanging, the exact bug these loops
        exist to prevent."""
        try:
            fut.set_exception(err)
        except Exception:  # noqa: BLE001 — already resolved/cancelled: fine
            pass

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Stop the worker (drains whatever is queued, rejecting anything
        still unserved at join timeout with :class:`EngineStopped`).
        Idempotent; also runs via atexit if the owner forgot."""
        import atexit

        atexit.unregister(self.stop)
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._worker.join(timeout=join_timeout_s)
        if self._worker.is_alive():
            # the worker is wedged inside a dispatch: the in-flight batch
            # will never resolve on its own — fail those waiters too (the
            # queue drain below only covers never-popped requests)
            with self._cv:
                inflight, self._inflight = self._inflight, []
            for p in inflight:
                self._fail_future(
                    p.future, EngineStopped("batcher stopped mid-flight")
                )
                p.span.end(error="stopped mid-flight")
                self._release_tenant(p)
        with self._cv:
            while self._q:
                p = self._q.popleft()
                self._fail_future(p.future, EngineStopped("batcher stopped"))
                p.span.end(error="stopped")
                self._release_tenant(p)

    # --- worker side ---

    def _loop(self) -> None:
        # the whole worker body is fault-contained: a top-level exception
        # (engine bug outside _flush's guarded call, metrics callback,
        # collector fault) previously killed this thread SILENTLY and every
        # queued/future request hung until client timeout. Now it fails all
        # pending futures with the typed WorkerCrashed and marks the
        # batcher stopped (submit rejects with EngineStopped from then on).
        try:
            while True:
                batch = self._collect()
                if batch is None:
                    return
                self._flush(batch)
                # every future resolved; drop the refs UNDER the cv —
                # stop()/_worker_crashed read _inflight under it from
                # other threads, and an unlocked reset here raced them
                # (host-lock-discipline; pinned in test_analysis_host)
                with self._cv:
                    self._inflight = []
        except BaseException as e:  # noqa: BLE001 — fail pending, then die
            self._worker_crashed(e)

    def _worker_crashed(self, exc: BaseException) -> None:
        err = WorkerCrashed(
            f"serve batcher worker crashed: {type(exc).__name__}: {exc}"
        )
        with self._cv:
            self._stopped = True
            pending = list(self._inflight) + list(self._q)
            self._inflight = []
            self._q.clear()
            self._cv.notify_all()
        for p in pending:
            self._fail_future(p.future, err)
            p.span.end(error="worker_crashed")
            self._release_tenant(p)
        # best-effort observability: the registry itself may be what crashed
        try:
            self.registry.counter("serve.worker_crashed")
        except Exception:  # noqa: BLE001
            pass
        import sys

        print(f"[serve] {err} ({len(pending)} pending failed)",
              file=sys.stderr, flush=True)

    def _collect(self):
        """Block until a batch is ready per the flush policy; None = exit."""
        with self._cv:
            while not self._q:
                if self._stopped:
                    return None
                self._cv.wait(0.1)
            # batch window: fill up to max_batch_size or until the OLDEST
            # request has waited max_delay_ms (per-batch added latency is
            # bounded by the delay knob, not by arrival luck)
            flush_at = self._q[0].enqueued_at + self.max_delay_ms / 1e3
            while len(self._q) < self.max_batch_size and not self._stopped:
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            # pop INTO the inflight list (not a local): anything that
            # raises from here until _flush resolves the futures must leave
            # them reachable for _worker_crashed
            batch = self._inflight = []
            total = 0
            cap = self.engine.ladder.max_size
            popped_at = time.monotonic()
            while self._q and len(batch) < self.max_batch_size:
                nxt = self._q[0]
                if batch and total + nxt.ids.shape[0] > cap:
                    break  # would overflow the largest bucket; next batch
                p = self._q.popleft()
                p.popped_at = popped_at  # queue-wait ends here
                batch.append(p)
                total += nxt.ids.shape[0]
            self.registry.gauge("serve.queue_depth", float(len(self._q)))
            return batch

    def _revalidate(self, eng, p: _Pending):
        """Re-check one queued request against the engine that will ACTUALLY
        run it. Submit-time validation ran against whatever engine was
        active then; a registry flip (rollback to a smaller graph, a
        replacement ladder) between submit and flush would otherwise let a
        stale request reach the engine, where its failure fans out to every
        innocent request coalesced into the same batch. Returns the
        structured error to fail JUST this request with, or None."""
        try:
            eng.ladder.bucket_for(p.ids.shape[0])
        except RequestTooLarge as e:
            return e
        num_nodes = getattr(eng, "num_nodes", None)
        if num_nodes is not None and p.ids.size and (
            p.ids.min() < 0 or p.ids.max() >= num_nodes
        ):
            return ValueError(
                f"node ids must be in [0, {num_nodes}) on the engine now "
                f"active, got [{p.ids.min()}, {p.ids.max()}]"
            )
        return None

    def _flush(self, batch) -> None:
        now = time.monotonic()
        # resolve the active engine ONCE per flush: a registry activate()
        # landing mid-flush must not split one batch across two engines
        eng = self.engine
        live = []
        for p in batch:
            # a client-cancelled future is dropped exactly like an expired
            # one: its client already gave up, and resolving a cancelled
            # Future raises InvalidStateError — which the worker's crash
            # containment would escalate into stopping the whole batcher
            # (one impatient client must never take the queue down).
            # set_running_or_notify_cancel() atomically claims the future,
            # closing the race where cancel() lands after this check.
            if not p.future.set_running_or_notify_cancel():
                self.registry.counter("serve.rejected_cancelled")
                p.span.end(error="cancelled")
                self._release_tenant(p)
                continue
            if now > p.deadline:
                self.registry.counter("serve.rejected_timeout")
                p.future.set_exception(
                    RequestTimeout(
                        f"request expired after {now - p.enqueued_at:.3f}s in "
                        "queue (timeout "
                        f"{p.deadline - p.enqueued_at:.3f}s)",
                        waited_s=round(now - p.enqueued_at, 4),
                    )
                )
                p.span.end(error="timeout",
                           queue_wait_ms=round((now - p.enqueued_at) * 1e3, 3))
                self._release_tenant(p)
                continue
            stale_err = self._revalidate(eng, p)
            if stale_err is not None:
                self.registry.counter("serve.rejected_stale")
                p.future.set_exception(stale_err)
                p.span.end(error=getattr(stale_err, "code", "bad_ids"))
                self._release_tenant(p)
                continue
            live.append(p)
        if not live:
            return  # expired/cancelled-only batch: flush empty, no engine call
        # per-request stage times: queue_wait (enqueue -> worker pop) and
        # batch_form (pop -> flush start); pad/infer come back from the
        # engine as batch-level numbers and reply is the fan-out below
        for p in live:
            popped = p.popped_at or now
            self.registry.histogram(
                "serve.stage.queue_wait_ms", (popped - p.enqueued_at) * 1e3
            )
            self.registry.histogram(
                "serve.stage.batch_form_ms", max(now - popped, 0.0) * 1e3
            )
        # re-chunk against the RESOLVED engine's largest bucket: _collect
        # split against the engine active at pop time, and a flip to a
        # shorter (entry-replacing register) ladder between pop and flush
        # would otherwise overflow the bucket for the whole batch
        cap = eng.ladder.max_size
        chunk, total = [], 0
        for p in live:
            n = int(p.ids.shape[0])
            if chunk and total + n > cap:
                self._dispatch(eng, chunk, now)
                chunk, total = [], 0
            chunk.append(p)
            total += n
        self._dispatch(eng, chunk, now)

    def _dispatch(self, eng, live, now: float) -> None:
        ids = np.concatenate([p.ids for p in live]) if len(live) > 1 else live[0].ids
        try:
            # the batch span is the worker thread's ambient span, so the
            # engine's serve.infer span parents under it
            with spans.span("serve.batch", requests=len(live),
                            n=int(ids.shape[0])):
                out = eng.infer(ids)
        except Exception as e:  # noqa: BLE001 — fan the failure to every waiter
            err_label = f"{type(e).__name__}: {e}"
            # engine-level STRUCTURED rejections (backpressure, degraded
            # shed) are the ENGINE's state, not any tenant's payload —
            # booking them as tenant failures would let a backend outage
            # degrade every innocent tenant. Only raw engine exceptions
            # feed the per-tenant consecutive-failure streak (where
            # collateral hits from a co-batched poisoner wash out while
            # the poisoner's own streak accumulates).
            from dgraph_tpu.serve.errors import ServeError

            tenant_fault = not isinstance(e, ServeError)
            for p in live:
                p.future.set_exception(e)
                p.span.end(error=err_label[:200])
                self._release_tenant(
                    p, success=False if tenant_fault else None
                )
            return
        stage = getattr(eng, "last_stage_ms", {})
        off = 0
        reply_t0 = time.monotonic()
        for p in live:
            n = p.ids.shape[0]
            p.future.set_result(out[off : off + n])
            off += n
        done = time.monotonic()
        reply_ms = (done - reply_t0) * 1e3
        self.registry.histogram("serve.stage.reply_ms", reply_ms)
        for p in live:
            popped = p.popped_at or now
            self.registry.histogram(
                "serve.request_ms", (done - p.enqueued_at) * 1e3
            )
            if p.tenant is not None:
                # per-tenant end-to-end latency: the p99-under-contention
                # artifact serve_bench's multi-tenant mode reports
                self.registry.histogram(
                    f"serve.tenant.{p.tenant}.request_ms",
                    (done - p.enqueued_at) * 1e3,
                )
            self._release_tenant(p, success=True)
            p.span.end(
                queue_wait_ms=round((popped - p.enqueued_at) * 1e3, 3),
                batch_form_ms=round(max(now - popped, 0.0) * 1e3, 3),
                pad_ms=round(stage.get("pad", 0.0), 3),
                infer_ms=round(stage.get("infer", 0.0), 3),
                reply_ms=round(reply_ms, 3),
                batch_size=len(live),
            )
        self.registry.counter("serve.batches")
        self.registry.histogram("serve.requests_per_batch", float(len(live)))
