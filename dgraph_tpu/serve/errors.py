"""Structured serving errors.

Every rejection path in the serving stack raises a typed :class:`ServeError`
whose :meth:`~ServeError.record` form is a JSONL-able dict — the same
"structured record over free-text stderr" discipline :mod:`dgraph_tpu.obs.
health` established for run diagnostics. Callers (and load generators)
branch on ``.code``, logs get one parseable line per rejection, and nothing
ever queues unboundedly just because raising felt impolite.
"""

from __future__ import annotations


class ServeError(Exception):
    """Base serving error; ``record()`` is the structured JSONL form."""

    code = "error"

    def __init__(self, message: str, **context):
        super().__init__(message)
        self.context = context

    def record(self) -> dict:
        return {
            "kind": "serve_error",
            "error": self.code,
            "detail": str(self),
            **self.context,
        }


class RequestTooLarge(ServeError):
    """Request exceeds the largest shape bucket. Admitting it would force a
    fresh XLA compile on the hot path (the one thing the bucket ladder
    exists to prevent), so it is rejected at submit time; the client should
    split the request or the operator should raise ``max_bucket``."""

    code = "too_large"


class QueueFull(ServeError):
    """Backpressure: the bounded request queue is at capacity. Rejected
    immediately so the client can retry/shed load — queue depth, not queue
    growth, is the knob (an unbounded queue converts overload into
    unbounded latency for everyone)."""

    code = "backpressure"


class RequestTimeout(ServeError):
    """The request aged past its deadline while waiting in the queue; it is
    rejected without running (serving stale work wastes a batch slot the
    client has already given up on)."""

    code = "timeout"


class EngineStopped(ServeError):
    """The batcher/engine was shut down while the request was in flight."""

    code = "stopped"


class QuotaExceeded(ServeError):
    """Per-tenant admission control said no: the tenant's token bucket is
    empty (rate quota) or its share of the bounded queue is full (space
    quota). Rejected at submit so ONE tenant's flood degrades only that
    tenant — every other tenant's requests keep flowing through the same
    batcher (:mod:`dgraph_tpu.serve.tenancy`)."""

    code = "quota"


class TenantDegraded(ServeError):
    """This tenant is shed because its own recent requests kept failing
    (poisoned payloads, systematic bad ids): the per-tenant analog of the
    engine's global degraded mode. Other tenants are unaffected; the
    operator re-admits with ``TenantTable.reset(tenant)``."""

    code = "tenant_degraded"


class SwapRejected(ServeError):
    """A checkpoint hot-swap (:meth:`~dgraph_tpu.serve.engine.ServeEngine.
    swap_params`) was refused or rolled back — structural mismatch with the
    warmed executables, non-finite parameters, served!=eval parity failure,
    or a fault mid-validation. The PRIOR params remain installed (the swap
    validates against the staged tree and only flips the live pointer after
    every oracle passes), so serving continues uninterrupted on the old
    checkpoint."""

    code = "swap_rejected"


class WorkerCrashed(ServeError):
    """The micro-batcher's worker thread died on an unexpected exception
    (engine bug, metrics callback, collector fault). Every pending and
    in-flight request fails fast with this error and the batcher marks
    itself stopped — the alternative (a silently dead worker) left every
    queued future hanging until its client timeout."""

    code = "worker_crashed"
